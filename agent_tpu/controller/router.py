"""Stateless HTTP router fronting N controller partitions (ISSUE 18).

``python -m agent_tpu.controller.router`` is the one address clients and
agents see: it proxies the write path (``/v1/jobs``, ``/v1/infer``,
``/v1/leases``, ``/v1/results``) to the home partition picked by the
consistent hash in ``controller/partition.py`` and fans out + merges the
read path (``/v1/status``, ``/v1/health``, ``/v1/usage``, ``/v1/metrics``,
``/v1/timeseries``, ``/v1/debug/requests``) so the fleet reads as one
controller. By-id lookups (``/v1/jobs/<id>``, ``/v1/infer/<id>``,
``/v1/trace/<id>``) fan out and return the first partition that knows the
id.

The router holds no durable state — placement is a pure hash, lease
routing rides the ``<partition>!<lease_id>`` tags, and per-partition depth
samples are a TTL cache — so it can be restarted (or replicated) freely;
robustness lives in the partitions' own journals and standbys. 429s
aggregate by construction: only the home partition is asked, so a submit
is rejected exactly when its home partition rejects it, and the
partition's ``retry_after_ms`` (and ``Retry-After`` header) pass through
untouched, with the partition name stamped into the body so loadgen can
count drops per partition.

Deployment modes (env):

- ``PARTITION_URLS="p0=http://a|http://a-standby,p1=http://b"`` — front an
  existing fleet of ``python -m agent_tpu.controller.server`` processes
  (each started with ``CONTROLLER_PARTITION=<name>`` and its own
  ``CONTROLLER_JOURNAL``); the ``|`` alternates are each partition's
  failover slots (where its promoted hot standby serves).
- ``PARTITIONS=N`` (no URLs) — boot N in-process partitions on ephemeral
  ports (journals at ``$CONTROLLER_JOURNAL.pI``): the single-host
  convenience mode. For real throughput run one server process per
  partition — N partitions in one process share a GIL.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from agent_tpu.controller.partition import (
    LocalPartitionSet,
    PartitionDown,
    PartitionMap,
    RouterCore,
)
from agent_tpu.obs.metrics import parse_exposition
from agent_tpu.obs.timeseries import TimeSeriesRing
from agent_tpu.obs.tsdb import TsdbStore, query_history
from agent_tpu.sched.steal import StealPolicy

_VERDICT_RANK = {"ok": 0, "warn": 1, "page": 2}


def http_post_json(
    url: str, path: str, body: Dict[str, Any], timeout: float
) -> Tuple[int, Any]:
    """The RouterCore transport: POST JSON, return (status, parsed body).
    HTTP error statuses (429, 400, ...) are RESPONSES to pass through,
    not transport failures; only the OSError family (URLError, timeouts,
    refused connections) propagates for URL rotation."""
    data = json.dumps(body, default=str).encode()
    req = urllib.request.Request(
        url + path,
        data=data,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            status, raw = resp.status, resp.read()
    except urllib.error.HTTPError as exc:
        status, raw = exc.code, exc.read()
    except http.client.HTTPException as exc:
        # A partition dying mid-response surfaces as IncompleteRead /
        # RemoteDisconnected — http.client exceptions, NOT OSErrors. The
        # RouterCore's failover/PartitionDown handling keys on OSError, so
        # normalize.
        raise ConnectionError(f"partition died mid-response: {exc}") from exc
    try:
        parsed = json.loads(raw.decode("utf-8")) if raw else None
    except ValueError:
        parsed = None
    return status, parsed


def http_get_json(url: str, path: str, timeout: float) -> Tuple[int, Any]:
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as resp:
            status, raw = resp.status, resp.read()
    except urllib.error.HTTPError as exc:
        status, raw = exc.code, exc.read()
    except http.client.HTTPException as exc:
        raise ConnectionError(f"partition died mid-response: {exc}") from exc
    try:
        parsed = json.loads(raw.decode("utf-8")) if raw else None
    except ValueError:
        parsed = raw.decode("utf-8", errors="replace") if raw else None
    return status, parsed


# ---- fan-out merges ----


def _worst_verdict(verdicts: List[str]) -> str:
    return max(verdicts or ["ok"], key=lambda v: _VERDICT_RANK.get(v, 2))


def _sum_counts(
    docs: List[Dict[str, Any]], key: str
) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for doc in docs:
        for k, v in (doc.get(key) or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = out.get(k, 0) + int(v)
    return out


def deep_sum(docs: List[Any]) -> Any:
    """Generic numeric merge for /v1/usage: dicts merge recursively,
    numbers sum, lists concatenate, anything else keeps the first
    partition's value. Usage reports are per-tenant/per-op numeric
    rollups, which this folds exactly; stray strings (enabled flags,
    names) stay stable."""
    docs = [d for d in docs if d is not None]
    if not docs:
        return None
    first = docs[0]
    if isinstance(first, dict):
        keys: List[str] = []
        for d in docs:
            if isinstance(d, dict):
                for k in d:
                    if k not in keys:
                        keys.append(k)
        return {
            k: deep_sum([
                d.get(k) for d in docs if isinstance(d, dict) and k in d
            ])
            for k in keys
        }
    if isinstance(first, bool):
        return any(d for d in docs if isinstance(d, bool))
    if isinstance(first, (int, float)):
        total = sum(
            d for d in docs
            if isinstance(d, (int, float)) and not isinstance(d, bool)
        )
        return total if not isinstance(first, int) or isinstance(
            total, float
        ) else int(total)
    if isinstance(first, list):
        out: List[Any] = []
        for d in docs:
            if isinstance(d, list):
                out.extend(d)
        return out
    return first


def merge_status(
    results: Dict[str, Optional[Dict[str, Any]]],
    pmap: PartitionMap,
    router_stats: Dict[str, Any],
) -> Dict[str, Any]:
    """One /v1/status doc for the whole partitioned plane: fleet-summed
    counters, agents deduped by name (an agent that stole shows up in two
    partitions' registries), and one row per partition — queue depth,
    journal block, reachability — for swarmtop's partition table."""
    up = {n: d for n, d in results.items() if isinstance(d, dict)}
    docs = list(up.values())
    rows = []
    for name in pmap.names:
        doc = results.get(name)
        row: Dict[str, Any] = {
            "name": name,
            "url": pmap.urls(name)[0],
            "ok": isinstance(doc, dict),
        }
        if isinstance(doc, dict):
            row.update({
                "queue_depth": doc.get("queue_depth", 0),
                "counts": doc.get("counts") or {},
                "drained": bool(doc.get("drained")),
                "journal": doc.get("journal") or {},
            })
        rows.append(row)
    agents: Dict[str, Any] = {}
    for doc in docs:
        for name, row in (doc.get("agents") or {}).items():
            prev = agents.get(name)
            if prev is None or (
                row.get("last_seen_sec_ago", 1e9)
                < prev.get("last_seen_sec_ago", 1e9)
            ):
                agents[name] = row
    counts_by_op: Dict[str, Dict[str, int]] = {}
    for doc in docs:
        for op, per in (doc.get("counts_by_op") or {}).items():
            tgt = counts_by_op.setdefault(op, {})
            for state, n in per.items():
                tgt[state] = tgt.get(state, 0) + int(n)
    serving_docs = [
        doc.get("serving") for doc in docs
        if isinstance(doc.get("serving"), dict)
    ]
    serving: Dict[str, Any] = {
        "enabled": any(d.get("enabled") for d in serving_docs),
    }
    if serving["enabled"]:
        serving.update({
            "requests": _sum_counts(serving_docs, "requests"),
            "open_buckets": sum(
                int(d.get("open_buckets", 0)) for d in serving_docs
            ),
            "bucketed": sum(
                int(d.get("bucketed", 0)) for d in serving_docs
            ),
            "jobs_in_flight": sum(
                int(d.get("jobs_in_flight", 0)) for d in serving_docs
            ),
            "rejected": sum(
                int(d.get("rejected", 0)) for d in serving_docs
            ),
        })
    ops: Dict[str, Dict[str, Any]] = {}
    phases: Dict[str, Any] = {}
    uptime = 0.0
    for doc in docs:
        summary = doc.get("summary") or {}
        uptime = max(uptime, float(summary.get("uptime_sec") or 0.0))
        for op, entry in (summary.get("ops") or {}).items():
            tgt = ops.setdefault(
                op, {"succeeded": 0, "failed": 0, "tasks_per_sec": 0.0}
            )
            tgt["succeeded"] += int(entry.get("succeeded", 0))
            tgt["failed"] += int(entry.get("failed", 0))
            tgt["tasks_per_sec"] = round(
                tgt["tasks_per_sec"] + float(entry.get("tasks_per_sec", 0.0)),
                3,
            )
        for op, per in (summary.get("task_phase_seconds") or {}).items():
            phases.setdefault(op, per)
    return {
        "partitioned": True,
        "partitions": rows,
        "router": router_stats,
        "counts": _sum_counts(docs, "counts"),
        "counts_by_op": counts_by_op,
        "queue_depth": sum(int(d.get("queue_depth", 0)) for d in docs),
        # A fleet with an unreachable partition is NOT drained — its jobs
        # are unobservable, not done.
        "drained": len(up) == len(pmap.names)
        and all(bool(d.get("drained")) for d in docs),
        "stale_results": sum(int(d.get("stale_results", 0)) for d in docs),
        "agents": agents,
        "summary": {
            "uptime_sec": uptime,
            "ops": ops,
            "task_phase_seconds": phases,
        },
        "journal": {
            name: (results[name] or {}).get("journal") or {}
            for name in pmap.names
            if isinstance(results.get(name), dict)
        },
        "serving": serving,
        "last_metrics": {},
    }


def merge_health(
    results: Dict[str, Optional[Dict[str, Any]]],
    pmap: PartitionMap,
) -> Dict[str, Any]:
    """One /v1/health verdict: the WORST partition wins, an unreachable
    partition pages (its jobs and journal are dark), reasons carry their
    partition, objectives concatenate suffixed ``@partition``."""
    up = {n: d for n, d in results.items() if isinstance(d, dict)}
    docs = list(up.values())
    verdicts = [str(d.get("verdict", "page")) for d in docs]
    reasons: List[Dict[str, Any]] = []
    rows = []
    for name in pmap.names:
        doc = results.get(name)
        ok = isinstance(doc, dict)
        rows.append({
            "name": name,
            "ok": ok,
            "verdict": str(doc.get("verdict")) if ok else "page",
        })
        if not ok:
            verdicts.append("page")
            reasons.append({
                "kind": "partition_unreachable", "partition": name,
            })
            continue
        for reason in doc.get("reasons") or []:
            reasons.append(dict(reason, partition=name))
    objectives: List[Dict[str, Any]] = []
    for name, doc in up.items():
        for obj in (doc.get("slo") or {}).get("objectives") or []:
            entry = dict(obj)
            if len(pmap.names) > 1:
                entry["objective"] = f"{obj.get('objective')}@{name}"
            objectives.append(entry)
    agents: Dict[str, Any] = {}
    for doc in docs:
        for name, row in (doc.get("agents") or {}).items():
            prev = agents.get(name)
            if prev is None or (
                row.get("last_seen_sec_ago", 1e9)
                < prev.get("last_seen_sec_ago", 1e9)
            ):
                agents[name] = row
    by_tier: Dict[str, int] = {}
    starvation: Optional[float] = None
    for doc in docs:
        q = doc.get("queue") or {}
        for tier, n in (q.get("by_tier") or {}).items():
            by_tier[tier] = by_tier.get(tier, 0) + int(n)
        age = q.get("starvation_age_sec")
        if isinstance(age, (int, float)):
            starvation = max(starvation or 0.0, float(age))
    return {
        "verdict": _worst_verdict(verdicts),
        "reasons": reasons,
        "generated_at": round(time.time(), 3),
        "partitioned": True,
        "partitions": rows,
        "slo": {
            "enabled": any(
                (d.get("slo") or {}).get("enabled") for d in docs
            ),
            "objectives": objectives,
        },
        "queue": {
            "depth": sum(
                int((d.get("queue") or {}).get("depth", 0)) for d in docs
            ),
            "by_tier": by_tier,
            "starvation_age_sec": starvation,
        },
        "counts": _sum_counts(docs, "counts"),
        "fleet": {
            "n_agents": len(agents),
            "n_stale": sum(1 for r in agents.values() if r.get("stale")),
        },
        "agents": agents,
    }


def _escape_label(value: Any) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def merge_metrics(
    texts: Dict[str, Optional[str]], router_stats: Dict[str, Any]
) -> str:
    """One exposition for the plane: every partition's samples re-emitted
    with a ``partition`` label (cumulative families sum correctly across
    label sets downstream — swarmtop's quantile/total helpers already
    merge label sets), plus the router's own counters. Untyped on purpose:
    HELP/TYPE metadata doesn't survive a merge of N sources cleanly, and
    every consumer in this repo parses samples, not metadata."""
    lines: List[str] = []
    for name in sorted(texts):
        text = texts[name]
        if not text:
            continue
        try:
            samples = parse_exposition(text)
        except ValueError:
            continue
        for family in sorted(samples):
            for labels, value in samples[family]:
                merged = dict(labels, partition=name)
                label_s = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(merged.items())
                )
                lines.append(f"{family}{{{label_s}}} {float(value)!r}")
    for key, value in sorted(router_stats.items()):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        lines.append(f"router_{key} {float(value)!r}")
    return "\n".join(lines) + "\n"


# ---- fleet telemetry collection (ISSUE 20 tentpole b) ----


def _relabel_partition(
    data: Dict[str, Any], partition: str
) -> Dict[str, Dict[str, float]]:
    """Inject ``partition=<name>`` into every series label key of one
    scraped sample — the fleet store's series identity."""
    out: Dict[str, Dict[str, float]] = {}
    for fam, series in (data or {}).items():
        if not isinstance(series, dict):
            continue
        dst = out.setdefault(fam, {})
        for key, v in series.items():
            try:
                labels = [
                    list(p) for p in json.loads(key)
                    if isinstance(p, (list, tuple)) and len(p) == 2
                    and p[0] != "partition"
                ]
            except ValueError:
                continue
            labels.append(["partition", partition])
            dst[json.dumps(sorted(labels), separators=(",", ":"))] = \
                float(v)
    return out


class FleetCollector:
    """Scrapes each partition's ``/v1/timeseries/export`` deltas into one
    fleet store (``partition``-labelled), so the router's
    ``GET /v1/timeseries?since=`` answers fleet-wide historical queries —
    the durable follow-up to the live-only fan-out merge. One wall-clock
    cursor per partition; a partition restart resets its ring but not the
    cursor (walls are wall-clock, so history never replays twice)."""

    def __init__(
        self,
        pmap: PartitionMap,
        interval_sec: float = 10.0,
        window_sec: float = 900.0,
        tsdb_dir: str = "",
        timeout_sec: float = 5.0,
        get_fn: Optional[Any] = None,
    ) -> None:
        self.pmap = pmap
        self.interval_sec = max(0.25, float(interval_sec))
        self.timeout_sec = timeout_sec
        self.get_fn = get_fn if get_fn is not None else http_get_json
        # The fleet ring holds len(pmap) partitions' samples per scrape
        # round — size its slot budget accordingly.
        self.ring = TimeSeriesRing(
            window_sec=max(self.interval_sec, float(window_sec)),
            interval_sec=self.interval_sec / max(1, len(pmap.names)),
        )
        self.store: Optional[TsdbStore] = None
        if tsdb_dir:
            self.store = TsdbStore(tsdb_dir)
        self._cursors: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scrapes = 0
        self.scrape_errors = 0
        self.samples_collected = 0

    def collect_once(self) -> int:
        """One scrape round across all partitions; returns samples
        collected. Each partition's failover slots are tried in order —
        a promoted standby keeps feeding the fleet view."""
        collected = 0
        for name in self.pmap.names:
            cursor = self._cursors.get(name, 0.0)
            doc = None
            for url in self.pmap.urls(name):
                try:
                    status, parsed = self.get_fn(
                        url,
                        f"/v1/timeseries/export?since={cursor!r}",
                        self.timeout_sec,
                    )
                except (OSError, ConnectionError):
                    continue
                if status == 200 and isinstance(parsed, dict):
                    doc = parsed
                    break
            self.scrapes += 1
            if doc is None:
                self.scrape_errors += 1
                continue
            for sample in doc.get("samples") or []:
                if not isinstance(sample, dict):
                    continue
                wall = sample.get("wall")
                if not isinstance(wall, (int, float)):
                    continue
                data = _relabel_partition(sample.get("data") or {}, name)
                self.ring.append_flat(float(wall), data)
                if self.store is not None:
                    self.store.append_sample(float(wall), data)
                cursor = max(cursor, float(wall))
                collected += 1
            self._cursors[name] = cursor
        self.samples_collected += collected
        return collected

    def query(
        self,
        name: str,
        label_filter: Optional[Dict[str, str]] = None,
        rate: bool = False,
        since: Optional[float] = None,
        step: Optional[float] = None,
    ) -> Dict[str, Any]:
        out = query_history(
            name, label_filter=label_filter, rate=rate,
            since=since, step=step, ring=self.ring, store=self.store,
        )
        out["fleet"] = True
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "interval_sec": self.interval_sec,
            "scrapes": self.scrapes,
            "scrape_errors": self.scrape_errors,
            "samples_collected": self.samples_collected,
            "cursors": {k: round(v, 3) for k, v in self._cursors.items()},
            "store": self.store.stats() if self.store is not None else None,
        }

    def start(self) -> "FleetCollector":
        if self._thread is None:
            self._stop.clear()

            def loop() -> None:
                while not self._stop.wait(self.interval_sec):
                    try:
                        self.collect_once()
                    except Exception:  # noqa: BLE001 — a scrape hiccup
                        # must not kill the collector; next round retries.
                        self.scrape_errors += 1

            self._thread = threading.Thread(
                target=loop, name="fleet-collector", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.store is not None:
            self.store.close()


# ---- the HTTP process ----


class _RouterHandler(BaseHTTPRequestHandler):
    core: RouterCore              # set by RouterServer on the built class
    fanout_timeout_sec: float = 5.0
    collector: Optional[FleetCollector] = None  # set by RouterServer

    def log_message(self, *args: Any) -> None:
        pass

    def _read_json(self) -> Optional[Dict[str, Any]]:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length) if length else b"{}"
            body = json.loads(raw or b"{}")
        except (ValueError, OSError):
            return None
        return body if isinstance(body, dict) else None

    def _send(self, status: int, body: Any = None) -> None:
        self.send_response(status)
        if body is None:
            self.end_headers()
            return
        data = json.dumps(body, default=str).encode()
        self.send_header("Content-Type", "application/json")
        if status == 429 and isinstance(body, dict):
            # Pass the partition's backpressure hint through header-level
            # too, matching the controller's own 429 shape.
            retry_ms = body.get("retry_after_ms")
            if isinstance(retry_ms, (int, float)):
                self.send_header(
                    "Retry-After", str(max(1, (int(retry_ms) + 999) // 1000))
                )
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_text(self, status: int, text: str, content_type: str) -> None:
        data = text.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # ---- fan-out helpers ----

    def _fanout_get(self, path: str) -> Dict[str, Any]:
        """GET ``path`` from every partition; unreachable/parse-failed
        partitions map to None."""
        core = self.core
        out: Dict[str, Any] = {}
        for name in core.pmap.names:
            try:
                status, parsed = core.get_partition(name, path)
            except (PartitionDown, OSError):
                out[name] = None
                continue
            out[name] = parsed if status == 200 else None
        return out

    def _first_found(self, path: str) -> None:
        """By-id lookups: the owning partition answers 200, the rest 404 —
        return the first 200 (or the last 404)."""
        last: Tuple[int, Any] = (404, {"error": f"no partition knows {path}"})
        for name in self.core.pmap.names:
            try:
                status, parsed = self.core.get_partition(name, path)
            except (PartitionDown, OSError):
                continue
            if status == 200:
                self._send(200, parsed)
                return
            last = (status, parsed)
        self._send(last[0], last[1] if isinstance(last[1], dict) else None)

    def _proxy_stream_infer(self, body: Dict[str, Any]) -> None:
        """stream:true /v1/infer — relay the partition's chunked NDJSON
        lifecycle stream byte-for-byte (urllib de-chunks; we re-frame)."""
        core = self.core
        params = body.get("params")
        tenant = body.get("tenant") or (
            params.get("tenant") if isinstance(params, dict) else None
        )
        name = core.home_for_tenant(tenant)
        url = core.pmap.urls(name)[0]
        req = urllib.request.Request(
            url + "/v1/infer",
            data=json.dumps(body, default=str).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            upstream = urllib.request.urlopen(req, timeout=None)
        except urllib.error.HTTPError as exc:
            raw = exc.read()
            try:
                self._send(exc.code, json.loads(raw.decode()))
            except ValueError:
                self._send(exc.code, {"error": raw.decode(errors="replace")})
            return
        except OSError:
            self._send(503, {"error": f"partition {name} unreachable"})
            return
        with upstream:
            self.send_response(upstream.status)
            self.send_header(
                "Content-Type",
                upstream.headers.get("Content-Type", "application/x-ndjson"),
            )
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                while True:
                    chunk = upstream.read(65536)
                    if not chunk:
                        break
                    self.wfile.write(
                        f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n"
                    )
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                pass

    # ---- HTTP surface ----

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        body = self._read_json()
        if body is None:
            self._send(400, {"error": "invalid JSON body"})
            return
        core = self.core
        try:
            if self.path == "/v1/jobs":
                status, parsed = core.route_submit(body)
            elif self.path == "/v1/workflows":
                status, parsed = core.route_workflow(body)
            elif self.path == "/v1/leases":
                status, parsed = core.route_lease(body)
            elif self.path == "/v1/results":
                status, parsed = core.route_result(body)
            elif self.path == "/v1/infer":
                if body.get("stream"):
                    self._proxy_stream_infer(body)
                    return
                status, parsed = core.route_infer(body)
            elif self.path == "/v1/profile/capture":
                # Capture requests target an agent, and any partition that
                # agent leases from can deliver the alert — hand it to the
                # agent's home partition.
                name = core.home_for_agent(str(body.get("agent") or ""))
                status, parsed = core.post_partition(
                    name, "/v1/profile/capture", body
                )
            else:
                self._send(404, {"error": f"no route {self.path}"})
                return
        except PartitionDown as exc:
            self._send(
                503,
                {"error": str(exc), "partition": exc.partition},
            )
            return
        if status == 204:
            self._send(204)
        else:
            self._send(
                status, parsed if isinstance(parsed, (dict, list)) else None
            )

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        core = self.core
        path = self.path
        if path == "/v1/status":
            self._send(200, merge_status(
                self._fanout_get("/v1/status"), core.pmap, core.stats()
            ))
        elif path == "/v1/health":
            self._send(
                200, merge_health(self._fanout_get("/v1/health"), core.pmap)
            )
        elif path.startswith("/v1/usage"):
            results = self._fanout_get(path)
            docs = [d for d in results.values() if isinstance(d, dict)]
            merged = deep_sum(docs) if docs else {"enabled": False}
            merged["partitions"] = {
                name: {
                    "ok": isinstance(doc, dict),
                    "billed_tasks": (doc or {}).get("billed_tasks"),
                }
                for name, doc in results.items()
            }
            self._send(200, merged)
        elif path == "/v1/metrics":
            texts = {}
            for name in core.pmap.names:
                try:
                    status, parsed = core.get_partition(name, "/v1/metrics")
                except (PartitionDown, OSError):
                    texts[name] = None
                    continue
                texts[name] = parsed if (
                    status == 200 and isinstance(parsed, str)
                ) else None
            self._send_text(
                200,
                merge_metrics(texts, core.stats()),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/v1/depth":
            depths = core.leasable_depths()
            self._send(200, {
                "partitions": depths,
                "leasable": sum(v or 0 for v in depths.values()),
            })
        elif path == "/v1/router":
            self._send(200, core.stats())
        elif path.startswith("/v1/timeseries"):
            split = urlsplit(path)
            q = parse_qs(split.query)
            if (
                self.collector is not None
                and split.path == "/v1/timeseries"
                and ("since" in q or "step" in q)
            ):
                # Historical fleet query (ISSUE 20): served from the
                # router's own collected store, partition-labelled.
                name = q.get("name", [None])[0]
                if not name:
                    self._send(400, {"error": "name is required"})
                    return
                try:
                    since = (
                        float(q["since"][0]) if "since" in q else None
                    )
                    step = float(q["step"][0]) if "step" in q else None
                except ValueError:
                    self._send(400, {
                        "error": "since/step must be numbers"
                    })
                    return
                if since is not None and since <= 1e6:
                    since = time.time() - max(0.0, since)
                rate = q.get("rate", ["0"])[0] in ("1", "true", "yes")
                label_filter = {
                    k: v[0] for k, v in q.items()
                    if k not in
                    ("name", "rate", "window_sec", "since", "step") and v
                }
                body = self.collector.query(
                    name, label_filter or None, rate=rate,
                    since=since, step=step,
                )
                body["enabled"] = True
                self._send(200, body)
                return
            results = self._fanout_get(path)
            series: List[Any] = []
            enabled = False
            name_field = None
            for doc in results.values():
                if not isinstance(doc, dict):
                    continue
                enabled = enabled or bool(doc.get("enabled"))
                name_field = name_field or doc.get("name")
                series.extend(doc.get("series") or [])
            self._send(
                200,
                {"enabled": enabled, "name": name_field, "series": series},
            )
        elif path == "/v1/incidents":
            results = self._fanout_get(path)
            incidents: List[Any] = []
            enabled = False
            for pname, doc in results.items():
                if not isinstance(doc, dict):
                    continue
                enabled = enabled or bool(doc.get("enabled"))
                for header in doc.get("incidents") or []:
                    if isinstance(header, dict):
                        header = dict(header)
                        header["partition"] = pname
                        incidents.append(header)
            incidents.sort(
                key=lambda h: (h.get("wall") or 0.0), reverse=True
            )
            self._send(200, {"enabled": enabled, "incidents": incidents})
        elif path.startswith("/v1/debug/requests"):
            results = self._fanout_get(path)
            merged_reqs: List[Any] = []
            enabled = False
            for doc in results.values():
                if not isinstance(doc, dict):
                    continue
                enabled = enabled or bool(doc.get("enabled"))
                merged_reqs.extend(doc.get("requests") or [])
            self._send(200, {"enabled": enabled, "requests": merged_reqs})
        elif path.startswith((
            "/v1/jobs/", "/v1/infer/", "/v1/trace/", "/v1/traces",
            "/v1/debug/events", "/v1/profile/", "/v1/workflows/",
            "/v1/incidents/",
        )):
            self._first_found(path)
        else:
            self._send(404, {"error": f"no route {path}"})


class RouterServer:
    """Owns a RouterCore + an HTTP server on a background thread — the
    router-side twin of ``ControllerServer`` (``port=0`` binds ephemeral;
    ``url`` is what CONTROLLER_URL(S) point at)."""

    def __init__(
        self,
        pmap: PartitionMap,
        host: str = "127.0.0.1",
        port: int = 0,
        steal: Optional[StealPolicy] = None,
        depth_cache_sec: float = 0.25,
        timeout_sec: float = 30.0,
        fanout_timeout_sec: float = 5.0,
        collect_interval_sec: float = 0.0,
        fleet_tsdb_dir: str = "",
        fleet_window_sec: float = 900.0,
    ) -> None:
        def post_fn(url, path, body, _timeout):  # noqa: ANN001
            return http_post_json(url, path, body, timeout_sec)

        def get_fn(url, path, _timeout):  # noqa: ANN001
            return http_get_json(url, path, fanout_timeout_sec)

        self.core = RouterCore(
            pmap,
            post_fn,
            get_fn=get_fn,
            steal=steal,
            depth_cache_sec=depth_cache_sec,
            timeout_sec=timeout_sec,
        )
        # Fleet telemetry collection (ISSUE 20): >0 scrapes each
        # partition's export deltas into one partition-labelled store.
        self.collector: Optional[FleetCollector] = None
        if collect_interval_sec > 0:
            self.collector = FleetCollector(
                pmap,
                interval_sec=collect_interval_sec,
                window_sec=fleet_window_sec,
                tsdb_dir=fleet_tsdb_dir,
                timeout_sec=fanout_timeout_sec,
            )
        handler = type(
            "Handler",
            (_RouterHandler,),
            {
                "core": self.core,
                "fanout_timeout_sec": fanout_timeout_sec,
                "collector": self.collector,
            },
        )
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "RouterServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="router-http", daemon=True
        )
        self._thread.start()
        if self.collector is not None:
            self.collector.start()
        return self

    def stop(self) -> None:
        if self.collector is not None:
            self.collector.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def main() -> int:
    """Standalone router: ``python -m agent_tpu.controller.router``. Env:
    ROUTER_HOST (default 0.0.0.0), ROUTER_PORT (default 8800),
    PARTITION_URLS (front an existing partition fleet) or PARTITIONS=N
    (boot N in-process partitions, journals at ``$CONTROLLER_JOURNAL.pI``),
    ROUTER_DEPTH_CACHE_SEC / ROUTER_TIMEOUT_SEC, and the STEAL_* knobs
    (see sched/steal.py)."""
    import signal

    from agent_tpu.config import (
        JournalConfig,
        ObsConfig,
        PartitionConfig,
        SchedConfig,
        ServeConfig,
        SloConfig,
        env_bool,
        env_float,
        env_int,
        env_str,
    )

    cfg = PartitionConfig.from_env()
    local: Optional[LocalPartitionSet] = None
    if cfg.partition_urls:
        pmap = PartitionMap.parse(cfg.partition_urls)
    elif cfg.partitions >= 1:
        journal = env_str("CONTROLLER_JOURNAL", "") or None
        sweep = env_float("CONTROLLER_SWEEP_SEC", 5.0)
        local = LocalPartitionSet(
            cfg.partitions,
            journal_base=journal,
            controller_kwargs=dict(
                lease_ttl_sec=env_float("LEASE_TTL_SEC", 30.0),
                sweep_interval_sec=sweep if sweep > 0 else None,
                max_attempts=max(1, env_int("MAX_ATTEMPTS", 2)),
                requeue_delay_sec=env_float("REQUEUE_DELAY_SEC", 1.0),
                sched=SchedConfig.from_env(),
                wire_binary=env_bool("WIRE_BINARY", True),
                slo=SloConfig.from_env(),
                obs=ObsConfig.from_env(),
                journal=JournalConfig.from_env(),
                serve=ServeConfig.from_env(),
            ),
        ).start()
        pmap = local.pmap
        assert pmap is not None
    else:
        print(
            "[agent-tpu-router] set PARTITION_URLS (front an existing "
            "fleet) or PARTITIONS=N (boot N in-process partitions)",
            flush=True,
        )
        return 2

    obs = ObsConfig.from_env()
    server = RouterServer(
        pmap,
        host=cfg.router_host,
        port=cfg.router_port,
        steal=StealPolicy.from_env(),
        depth_cache_sec=cfg.depth_cache_sec,
        timeout_sec=cfg.timeout_sec,
        # Fleet telemetry collection (ISSUE 20): ROUTER_COLLECT_SEC=0
        # disables; ROUTER_TSDB_DIR="" keeps the fleet view in-memory.
        collect_interval_sec=env_float(
            "ROUTER_COLLECT_SEC", obs.tsdb_interval_sec
        ),
        fleet_tsdb_dir=env_str("ROUTER_TSDB_DIR", "").strip(),
        fleet_window_sec=obs.tsdb_window_sec,
    )
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    server.start()
    mode = (
        f"{len(pmap)} in-process partition(s)" if local is not None
        else f"{len(pmap)} partition(s) via PARTITION_URLS"
    )
    print(
        f"[agent-tpu-router] routing on {server.url} for {mode}: "
        + ", ".join(
            f"{name}={pmap.urls(name)[0]}" for name in pmap.names
        ),
        flush=True,
    )
    stop.wait()
    server.stop()
    if local is not None:
        local.stop()
    print("[agent-tpu-router] stopped", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
