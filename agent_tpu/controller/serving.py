"""Online-serving front door: request coalescing for ``POST /v1/infer``.

The swarm's historical unit of work is a *shard* — hundreds of rows, seconds
of device time. ISSUE 15 adds the *request* story: a user posts ONE
classify/summarize request and wants an answer now. This module is the
controller half of that path:

- :class:`InferRequest` — one request's life: ``queued`` (waiting in a
  coalescing bucket) → ``batched`` (riding a submitted interactive-tier job)
  → ``done``/``failed``, with arrival/TTFT/latency stamps.
- :class:`ServeFrontDoor` — length-bucketed batch coalescing under a
  ``SERVE_MAX_WAIT_MS`` deadline + ``SERVE_MAX_BATCH`` cap. Requests bucket
  by ``(op, tenant, priority, decode-param signature, length bucket)`` so a
  flushed batch is one compiled shape with bounded padding waste; a bucket
  flushes the moment it fills, and the controller's lease/sweep cadence
  flushes deadline-expired remainders. The flushed batch becomes an
  ordinary job (``serve_classify`` / ``serve_summarize``) on the existing
  queue — interactive-tier priority via the fair scheduler, epoch fencing,
  journal, retries, and the 429 admission path all for free.

Threading: the front door owns ONE condition/lock guarding requests +
buckets + the job map. The controller never calls into it while holding its
own state lock (and vice versa), so lock order cannot invert. Completion
``notify_all``s the condition — the long-poll side of ``POST /v1/infer``
and ``GET /v1/infer/{id}?wait_ms=`` blocks on it.

Serving state is deliberately in-memory only: a request is an open HTTP
conversation, not durable work. The *batch jobs* journal like any job (so a
restarted controller finishes them), but their waiters are gone — the
completion fan-out for an unknown job id is a counted no-op.
"""

from __future__ import annotations

import collections
import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from agent_tpu.config import ServeConfig
from agent_tpu.sched import AdmissionError

# Request op → the leaseable op the flushed batch job carries. Serving ops
# are real registry ops (ops/serve_infer.py), so capability matching routes
# them exactly like any other op.
SERVE_OPS = {
    "classify": "serve_classify",
    "summarize": "serve_summarize",
}

# Decode/serving parameters a request may carry. Everything here is part of
# the bucket signature (one flushed batch = one compiled shape/config);
# ``max_length`` is deliberately NOT — it rides per request and becomes the
# continuous engine's per-slot token limit, which is exactly what lets short
# requests exit the running batch early.
BATCH_PARAM_KEYS = (
    "model_config", "num_beams", "min_length", "length_penalty",
    "early_stopping", "topk",
)
PER_REQUEST_PARAM_KEYS = ("max_length",)

QUEUED = "queued"
BATCHED = "batched"
DONE = "done"
FAILED = "failed"

# Completed requests retained for GET /v1/infer/{id} after the fact.
DONE_RETENTION = 4096


@dataclass
class InferRequest:
    req_id: str
    op: str                       # "classify" | "summarize"
    text: str
    params: Dict[str, Any]        # bucket-signature params
    max_length: Optional[int]
    tenant: str
    priority: int
    arrived_wall: float
    arrived_clock: float
    state: str = QUEUED
    job_id: Optional[str] = None
    batched_clock: Optional[float] = None
    result: Any = None
    error: Any = None
    ttft_ms: Optional[float] = None
    latency_ms: Optional[float] = None
    tokens: int = 0
    # ---- request-level observability (ISSUE 17) ----
    bucket: int = 0                         # length bucket the request rode
    flush_reason: Optional[str] = None      # "full" | "deadline"
    batched_wall: Optional[float] = None
    prefill_job_id: Optional[str] = None    # disagg: the serve_prefill leg
    # The request's OWN trace (trace_id = req_id): root "infer" span plus
    # the bucket-wait child, opened at submit, closed at flush/terminal.
    root_span_id: Optional[str] = None
    bucket_span_id: Optional[str] = None
    # Per-request decode telemetry the agent ships inside its batch result
    # entry (prefill/seat/first-token walls, KV wait, occupancy-at-join,
    # prefix cache hit, steps) — the TTFT decomposition's raw material.
    telemetry: Optional[Dict[str, Any]] = None

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "req_id": self.req_id,
            "op": self.op,
            "state": self.state,
            "tenant": self.tenant,
            "priority": self.priority,
            "job_id": self.job_id,
        }
        if self.state == DONE:
            out["result"] = self.result
            out["ttft_ms"] = self.ttft_ms
            out["latency_ms"] = self.latency_ms
            out["tokens"] = self.tokens
        elif self.state == FAILED:
            out["error"] = self.error
            out["latency_ms"] = self.latency_ms
        return out


@dataclass(frozen=True)
class _BucketKey:
    op: str
    tenant: str
    priority: int
    bucket: int          # padded input length (bytes — the byte tokenizer's unit)
    sig: str             # canonical JSON of the batch-level params


@dataclass
class ServeBatch:
    """One flushed bucket, ready to become a job."""

    key: _BucketKey
    requests: List[InferRequest]
    reason: str          # "full" | "deadline"

    def job_payload(self) -> Dict[str, Any]:
        payload: Dict[str, Any] = {
            "requests": [
                {
                    "req_id": r.req_id,
                    "text": r.text,
                    "arrived_wall": r.arrived_wall,
                    **(
                        {"max_length": r.max_length}
                        if r.max_length is not None else {}
                    ),
                }
                for r in self.requests
            ],
            "bucket": self.key.bucket,
        }
        payload.update(json.loads(self.key.sig))
        return payload


class ServeFrontDoor:
    """Request registry + length-bucketed coalescing (see module docstring).

    Every public method takes the front door's own lock; callers must NOT
    hold the controller state lock when calling in (the controller calls
    this before/after its locked sections, never inside them).
    """

    def __init__(
        self,
        config: Optional[ServeConfig] = None,
        clock=time.monotonic,
        traces=None,
        partition=None,
    ) -> None:
        self.config = config if config is not None else ServeConfig()
        self._clock = clock
        # Partitioned control plane (ISSUE 18): bucket keys already include
        # the tenant, so the router sends a tenant's whole serve stream to
        # one home partition and coalescing stays partition-local; the
        # partition name rides the generated req ids (and /v1/status via
        # stats()) so any req id names its owning partition.
        self.partition = str(partition) if partition else None
        # Controller's TraceStore (ISSUE 17): each request opens its own
        # trace (trace_id = req_id) with an "infer" root and a
        # "bucket.wait" child closed at flush time. None = tracing off.
        self._traces = traces
        self._cond = threading.Condition()
        self._requests: Dict[str, InferRequest] = {}
        self._buckets: "collections.OrderedDict[_BucketKey, List[InferRequest]]" = (
            collections.OrderedDict()
        )
        self._jobs: Dict[str, List[str]] = {}      # job_id -> req_ids
        self._done_ring: "collections.deque[str]" = collections.deque()
        self.rejected = 0

    # ---- intake ----

    def _bucket_len(self, text: str) -> int:
        n = len(text.encode("utf-8", errors="replace"))
        for edge in self.config.len_buckets:
            if n <= edge:
                return edge
        return self.config.len_buckets[-1]

    def _pending_count_locked(self) -> int:
        return sum(
            1 for r in self._requests.values()
            if r.state in (QUEUED, BATCHED)
        )

    def submit(
        self,
        op: str,
        text: Any,
        params: Optional[Dict[str, Any]] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        now_wall: Optional[float] = None,
    ) -> Tuple[InferRequest, List[ServeBatch]]:
        """Validate + enqueue one request. Returns the request and any
        bucket that FILLED on this enqueue (the caller submits those as
        jobs — outside this lock). Raises ``ValueError`` on a malformed
        request and ``AdmissionError`` (the wire's 429) past the pending
        budget."""
        if op not in SERVE_OPS:
            raise ValueError(
                f"op must be one of {sorted(SERVE_OPS)}, got {op!r}"
            )
        if not isinstance(text, str) or not text:
            raise ValueError("text must be a non-empty string")
        params = dict(params or {})
        unknown = set(params) - set(BATCH_PARAM_KEYS) - set(
            PER_REQUEST_PARAM_KEYS
        )
        if unknown:
            raise ValueError(f"unknown params: {sorted(unknown)}")
        max_length = params.pop("max_length", None)
        if max_length is not None and (
            isinstance(max_length, bool)
            or not isinstance(max_length, int) or max_length < 1
        ):
            raise ValueError("max_length must be a positive int")
        if tenant is not None and (
            not isinstance(tenant, str) or not tenant
        ):
            raise ValueError("tenant must be a non-empty string")
        if priority is not None and (
            isinstance(priority, bool) or not isinstance(priority, int)
            or not 0 <= priority <= 9
        ):
            raise ValueError("priority must be an int in [0, 9]")
        sig = json.dumps(params, sort_keys=True)
        now_wall = time.time() if now_wall is None else now_wall
        req = InferRequest(
            req_id=(
                f"req-{self.partition + '-' if self.partition else ''}"
                f"{uuid.uuid4().hex[:12]}"
            ),
            op=op,
            text=text,
            params=params,
            max_length=max_length,
            tenant=tenant if tenant is not None else "default",
            priority=(
                priority if priority is not None else self.config.priority
            ),
            arrived_wall=now_wall,
            arrived_clock=self._clock(),
        )
        key = _BucketKey(
            op=op, tenant=req.tenant, priority=req.priority,
            bucket=self._bucket_len(text), sig=sig,
        )
        req.bucket = key.bucket
        with self._cond:
            budget = self.config.max_pending
            if budget and self._pending_count_locked() + 1 > budget:
                self.rejected += 1
                raise AdmissionError(
                    f"serving pending budget exhausted "
                    f"({self._pending_count_locked()} in flight, budget "
                    f"{budget})",
                    retry_after_ms=int(self.config.max_wait_ms) or 1000,
                    tenant=req.tenant, scope="serving",
                )
            self._requests[req.req_id] = req
            self._buckets.setdefault(key, []).append(req)
            full: List[ServeBatch] = []
            if len(self._buckets[key]) >= self.config.max_batch:
                full.append(
                    ServeBatch(key, self._buckets.pop(key), reason="full")
                )
        # Open the request's OWN trace (outside the lock — the TraceStore
        # has its own; admission rejections above never mint spans). The
        # spans anchor at arrived_clock, so opening after enqueue costs no
        # timing accuracy.
        if self._traces is not None:
            req.root_span_id = self._traces.open(
                req.req_id, "infer", start_clock=req.arrived_clock,
                attributes={
                    "op": op, "tenant": req.tenant,
                    "priority": req.priority, "bucket": key.bucket,
                },
            )
            req.bucket_span_id = self._traces.open(
                req.req_id, "bucket.wait", req.root_span_id,
                start_clock=req.arrived_clock,
                attributes={"bucket": key.bucket},
            )
        return req, full

    def pop_due(self, now_clock: Optional[float] = None) -> List[ServeBatch]:
        """Buckets whose OLDEST request has waited out ``max_wait_ms`` —
        the deadline flush, driven by the controller's lease/sweep cadence.
        An empty queue stays idle: no buckets, no flushes, no work."""
        now = self._clock() if now_clock is None else now_clock
        deadline = self.config.max_wait_ms / 1e3
        out: List[ServeBatch] = []
        with self._cond:
            for key in list(self._buckets):
                reqs = self._buckets[key]
                if reqs and now - reqs[0].arrived_clock >= deadline:
                    out.append(
                        ServeBatch(key, self._buckets.pop(key),
                                   reason="deadline")
                    )
        return out

    def mark_batched(
        self,
        batch: ServeBatch,
        job_id: str,
        prefill_job_id: Optional[str] = None,
    ) -> None:
        now = self._clock()
        now_wall = time.time()
        with self._cond:
            self._jobs[job_id] = [r.req_id for r in batch.requests]
            for r in batch.requests:
                r.state = BATCHED
                r.job_id = job_id
                r.prefill_job_id = prefill_job_id
                r.batched_clock = now
                r.batched_wall = now_wall
                r.flush_reason = batch.reason
            self._cond.notify_all()
        # Close each rider's bucket-wait span with the flush verdict (why
        # did it leave the bucket: full or deadline) and the job it rides.
        if self._traces is not None:
            for r in batch.requests:
                attrs: Dict[str, Any] = {
                    "reason": batch.reason, "job_id": job_id,
                }
                if prefill_job_id:
                    attrs["prefill_job_id"] = prefill_job_id
                self._traces.finish(
                    r.req_id, r.bucket_span_id, now, attributes=attrs
                )

    def fail_batch(self, batch: ServeBatch, error: Any) -> List[InferRequest]:
        """A flushed batch whose job submission was refused (admission on
        the job queue): every rider fails with the refusal."""
        now = self._clock()
        with self._cond:
            for r in batch.requests:
                r.state = FAILED
                r.error = error
                r.latency_ms = round(
                    (now - r.arrived_clock) * 1e3, 3
                )
                self._retire_locked(r)
            self._cond.notify_all()
        if self._traces is not None:
            for r in batch.requests:
                self._traces.finish(
                    r.req_id, r.bucket_span_id, now,
                    attributes={"reason": "rejected"},
                )
        return list(batch.requests)

    # ---- completion fan-out ----

    def job_ids(self) -> List[str]:
        with self._cond:
            return list(self._jobs)

    def is_serve_job(self, job_id: str) -> bool:
        with self._cond:
            return job_id in self._jobs

    def complete_job(
        self, job_id: str, ok: bool, result: Any = None, error: Any = None
    ) -> List[InferRequest]:
        """Fan one terminal job's result out to its riding requests.
        Returns the requests that just completed (for metrics/SLO feeds).
        Unknown job ids (a replayed serve job from a dead incarnation, a
        non-serving job) return [] — a counted no-op at the caller."""
        with self._cond:
            req_ids = self._jobs.pop(job_id, None)
            if not req_ids:
                return []
            by_req: Dict[str, Any] = {}
            if ok and isinstance(result, dict):
                for entry in result.get("results") or []:
                    if isinstance(entry, dict) and entry.get("req_id"):
                        by_req[entry["req_id"]] = entry
            now = self._clock()
            completed: List[InferRequest] = []
            for rid in req_ids:
                req = self._requests.get(rid)
                if req is None or req.state in (DONE, FAILED):
                    continue
                entry = by_req.get(rid)
                if ok and entry is not None:
                    req.state = DONE
                    req.result = {
                        k: v for k, v in entry.items()
                        if k not in ("req_id", "telemetry")
                    }
                    tel = entry.get("telemetry")
                    req.telemetry = tel if isinstance(tel, dict) else None
                    ttft = entry.get("ttft_ms")
                    req.ttft_ms = (
                        round(float(ttft), 3)
                        if isinstance(ttft, (int, float)) else None
                    )
                    toks = entry.get("tokens")
                    req.tokens = (
                        int(toks) if isinstance(toks, (int, float)) else 0
                    )
                else:
                    req.state = FAILED
                    req.error = error if error is not None else {
                        "type": "MissingServeResult",
                        "message": "batch result carried no entry for "
                                   "this request",
                    }
                req.latency_ms = round((now - req.arrived_clock) * 1e3, 3)
                if req.ttft_ms is None and req.state == DONE:
                    # No agent-side stamp (e.g. classify on a legacy agent):
                    # first byte IS the completed answer.
                    req.ttft_ms = req.latency_ms
                self._retire_locked(req)
                completed.append(req)
            self._cond.notify_all()
        return completed

    def complete_cached(
        self,
        op: str,
        text: Any,
        result: Dict[str, Any],
        params: Optional[Dict[str, Any]] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> InferRequest:
        """Mint an already-DONE request for a front-door result-cache hit
        (ISSUE 19): the request never joins a bucket and never becomes a
        job — the cached batch entry IS the answer, delivered at submit
        time with TTFT ≈ 0. Only the fields the cache key does NOT cover
        (tenant/priority) need validating here: a hit implies the keyed
        fields (op/text/params) already passed ``submit`` validation once,
        byte-for-byte."""
        if op not in SERVE_OPS:
            raise ValueError(
                f"op must be one of {sorted(SERVE_OPS)}, got {op!r}"
            )
        if not isinstance(text, str) or not text:
            raise ValueError("text must be a non-empty string")
        if tenant is not None and (
            not isinstance(tenant, str) or not tenant
        ):
            raise ValueError("tenant must be a non-empty string")
        if priority is not None and (
            isinstance(priority, bool) or not isinstance(priority, int)
            or not 0 <= priority <= 9
        ):
            raise ValueError("priority must be an int in [0, 9]")
        now = self._clock()
        req = InferRequest(
            req_id=(
                f"req-{self.partition + '-' if self.partition else ''}"
                f"{uuid.uuid4().hex[:12]}"
            ),
            op=op,
            text=text,
            params=dict(params or {}),
            max_length=None,
            tenant=tenant if tenant is not None else "default",
            priority=(
                priority if priority is not None else self.config.priority
            ),
            arrived_wall=time.time(),
            arrived_clock=now,
        )
        req.bucket = self._bucket_len(text)
        req.state = DONE
        req.result = result
        toks = result.get("tokens") if isinstance(result, dict) else None
        req.tokens = int(toks) if isinstance(toks, (int, float)) else 0
        req.latency_ms = 0.0
        req.ttft_ms = 0.0
        with self._cond:
            self._requests[req.req_id] = req
            self._retire_locked(req)
            self._cond.notify_all()
        if self._traces is not None:
            req.root_span_id = self._traces.open(
                req.req_id, "infer", start_clock=now,
                attributes={
                    "op": op, "tenant": req.tenant,
                    "priority": req.priority, "bucket": req.bucket,
                },
            )
            self._traces.finish(
                req.req_id, req.root_span_id, now,
                attributes={"outcome": "completed", "cache_hit": True},
            )
        return req

    def _retire_locked(self, req: InferRequest) -> None:
        self._done_ring.append(req.req_id)
        while len(self._done_ring) > DONE_RETENTION:
            old = self._done_ring.popleft()
            if old != req.req_id:
                self._requests.pop(old, None)

    # ---- read side ----

    def get(self, req_id: str) -> Optional[InferRequest]:
        with self._cond:
            return self._requests.get(req_id)

    def snapshot(self, req_id: str) -> Optional[Dict[str, Any]]:
        with self._cond:
            req = self._requests.get(req_id)
            return req.snapshot() if req is not None else None

    def wait(
        self, req_id: str, timeout_sec: float
    ) -> Optional[Dict[str, Any]]:
        """Block until the request reaches a terminal state or the timeout
        elapses; returns the latest snapshot either way (None = unknown)."""
        deadline = time.monotonic() + max(0.0, timeout_sec)
        with self._cond:
            while True:
                req = self._requests.get(req_id)
                if req is None:
                    return None
                if req.state in (DONE, FAILED):
                    return req.snapshot()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return req.snapshot()
                self._cond.wait(timeout=min(remaining, 1.0))

    def wait_change(
        self, req_id: str, last_state: str, timeout_sec: float
    ) -> Optional[Dict[str, Any]]:
        """Block until the request's state differs from ``last_state`` (or
        timeout) — the chunked-streaming event loop's primitive."""
        deadline = time.monotonic() + max(0.0, timeout_sec)
        with self._cond:
            while True:
                req = self._requests.get(req_id)
                if req is None:
                    return None
                if req.state != last_state:
                    return req.snapshot()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return req.snapshot()
                self._cond.wait(timeout=min(remaining, 1.0))

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            states: Dict[str, int] = {}
            for r in self._requests.values():
                states[r.state] = states.get(r.state, 0) + 1
            out = {
                "requests": states,
                "open_buckets": len(self._buckets),
                "bucketed": sum(
                    len(v) for v in self._buckets.values()
                ),
                "jobs_in_flight": len(self._jobs),
                "rejected": self.rejected,
            }
            if self.partition:
                out["partition"] = self.partition
            return out
