"""Pipeline parallelism over a ``pp`` mesh axis — GPipe-style microbatching
as one SPMD program.

SURVEY.md §2.8 deferred pipeline parallelism ("the mesh API leaves an axis
open"); this module closes it the TPU way: no per-stage processes, no
send/recv runtime (the reference's world would use NCCL P2P here) — the whole
pipeline is a single jitted ``shard_map`` over the mesh, with
``jax.lax.ppermute`` shifting activations one stage forward per tick over ICI
and every stage running the same traced program (SPMD). XLA sees one static
loop (``lax.scan`` over ticks) and overlaps the permute with stage compute.

Layout:

- The per-layer block pytrees are **stacked**: each leaf gains a leading
  ``n_layers`` dim, reshaped to ``[pp, layers_per_stage, ...]`` and sharded
  ``P("pp")`` — so each device holds only its own stage's weights. That is
  the point of pp: a model too deep for one chip's HBM serves/trains with
  layers split across chips.
- Activations ride the schedule: microbatch ``m`` enters stage 0 at tick
  ``m``, reaches stage ``s`` at tick ``m + s``. Stage ``s`` at tick ``t``
  therefore processes microbatch ``t - s`` (bubble ticks compute on zeros and
  are discarded). After ``n_micro + pp - 1`` ticks the last stage has every
  output; a ``psum`` over ``pp`` (zeros elsewhere) hands the result to all
  stages.
- Composes with data parallelism: with a ``(dp, pp)`` mesh the microbatch
  batch dim shards over ``dp`` and each dp replica runs its own pipeline.

Bubble fraction is ``(pp - 1) / (n_micro + pp - 1)``; callers raise
``n_micro`` to amortize (default ``pp`` microbatches = the minimal schedule).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from agent_tpu.models import layers
from agent_tpu.models.layers import dot_product_attention
from agent_tpu.utils.compat import shard_map, stack_leaves


def stack_blocks(blocks: List[Any]) -> Any:
    """List of per-layer block pytrees → one pytree whose leaves carry a
    leading ``n_layers`` dim (scan-ready; reshaped per-stage by the caller).
    Staging goes through ``compat.stack_leaves``: the stacked leaves feed a
    ``P("pp")``-sharded shard_map operand, which legacy jax miscompiles for
    a traced concatenate."""
    return jax.tree_util.tree_map(lambda *ls: stack_leaves(ls), *blocks)


def stage_blocks(stacked: Any, pp: int) -> Any:
    """[n_layers, ...] leaves → [pp, n_layers/pp, ...]; dim 0 shards over pp."""

    def split(leaf):
        n = leaf.shape[0]
        if n % pp != 0:
            raise ValueError(f"n_layers {n} not divisible by pp={pp}")
        return leaf.reshape((pp, n // pp) + leaf.shape[1:])

    return jax.tree_util.tree_map(split, stacked)


def stage_specs(staged: Any) -> Any:
    """P("pp") on every leaf's leading (stage) dim, rest replicated."""
    return jax.tree_util.tree_map(lambda _: P("pp"), staged)


def pipeline_blocks(
    mesh,
    staged: Any,          # stage_blocks() output: leaves [pp, per_stage, ...]
    x: jax.Array,         # [B, L, D] activations (B divisible by n_micro·dp)
    mask: jax.Array,      # [B, L] int padding mask (1 = real)
    dtype: Any,
    attn_fn=dot_product_attention,
    n_micro: Optional[int] = None,
) -> jax.Array:
    """Apply the stacked encoder blocks through the pp pipeline → [B, L, D].

    Numerics match running the blocks sequentially (same ops, same order);
    tests assert equality against the dense forward.
    """
    pp = mesh.shape["pp"]
    dp = mesh.shape.get("dp", 1)
    n_micro = n_micro or pp
    B, L, D = x.shape
    if B % (n_micro * dp) != 0:
        raise ValueError(f"batch {B} not divisible by n_micro*dp={n_micro * dp}")
    xm = x.reshape(n_micro, B // n_micro, L, D)
    mm = mask.reshape(n_micro, B // n_micro, L)
    ticks = n_micro + pp - 1
    fwd = [(i, (i + 1) % pp) for i in range(pp)]

    def spmd(stage_params, xm, mm):
        # stage_params leaves: [1, per_stage, ...] (this stage's slice).
        local = jax.tree_util.tree_map(lambda l: l[0], stage_params)
        stage = jax.lax.axis_index("pp")

        def apply_stage(xb, mb):
            amask = layers.pad_mask_to_attn(mb)

            def body(h, block):
                return layers.encoder_block(
                    block, h, amask, dtype, attn_fn=attn_fn
                ), None

            out, _ = jax.lax.scan(body, xb, local)
            return out

        def tick(carry, t):
            prev_out, acc = carry
            # One hop forward around the ring; stage 0's incoming edge is
            # ignored (it reads the microbatch stream instead).
            shifted = jax.lax.ppermute(prev_out, "pp", fwd)
            m_idx = jnp.clip(t - stage, 0, n_micro - 1)
            x_in = jnp.where(stage == 0, xm[m_idx], shifted)
            y = apply_stage(x_in, mm[m_idx])
            out_idx = t - (pp - 1)
            valid = jnp.logical_and(stage == pp - 1, out_idx >= 0)
            written = acc.at[jnp.clip(out_idx, 0, n_micro - 1)].set(y)
            acc = jnp.where(valid, written, acc)
            return (y, acc), None

        zero = jnp.zeros(xm.shape[1:], dtype=xm.dtype)
        acc0 = jnp.zeros_like(xm)
        (_, acc), _ = jax.lax.scan(tick, (zero, acc0), jnp.arange(ticks))
        # Only the last stage accumulated; psum over pp broadcasts it.
        return jax.lax.psum(acc, "pp")

    out = shard_map(
        spmd,
        mesh=mesh,
        in_specs=(stage_specs(staged), P(None, "dp"), P(None, "dp")),
        out_specs=P(None, "dp"),
        # acc mixes pp-varying writes under a pp-varying predicate before the
        # final psum makes it invariant; the in/out specs are the contract.
        check_vma=False,
    )(staged, xm.astype(dtype), mm)
    return out.reshape(B, L, D)


def encoder_forward_pp(
    params: Any,
    ids: jax.Array,       # [B, L] int32
    mask: jax.Array,      # [B, L] int32 (1 = real)
    cfg,
    mesh,
    attn_fn=dot_product_attention,
    n_micro: Optional[int] = None,
) -> jax.Array:
    """``models.encoder.forward`` with the block stack pipelined over ``pp``.

    Embedding and the pooled head run data-parallel outside the shard_map
    (they are a tiny fraction of the FLOPs); only the depth — where a
    too-deep model actually exceeds one chip — is pipelined.
    """
    pp = mesh.shape["pp"]
    dtype = cfg.compute_dtype
    L = ids.shape[1]
    x = params["embed"].astype(dtype)[ids] + params["pos"][:L].astype(dtype)[None]
    staged = stage_blocks(stack_blocks(params["blocks"]), pp)
    x = pipeline_blocks(
        mesh, staged, x, mask, dtype, attn_fn=attn_fn, n_micro=n_micro
    )
    x = layers.layer_norm(params["ln_f"], x)
    denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1).astype(jnp.float32)
    pooled = (x.astype(jnp.float32) * mask[:, :, None]).sum(axis=1) / denom
    logits = layers.dense(params["head"], pooled.astype(dtype), dtype)
    return logits.astype(jnp.float32)
