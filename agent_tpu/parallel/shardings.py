"""Sharding specs for model pytrees over the canonical ``(dp, tp, sp)`` mesh.

The design recipe (scaling-book style): pick the mesh, annotate params and
batch with :class:`~jax.sharding.PartitionSpec`, and let XLA insert the
collectives — no hand-written all-reduces in the model code.

Layout choices for the encoder/seq2seq families:

- Attention projections ``wq/wk/wv`` are ``[d_model, heads, d_head]`` → heads
  shard over ``tp`` (Megatron-style column parallel); ``wo`` is
  ``[heads, d_head, d_model]`` → heads over ``tp`` (row parallel), so the
  block's only cross-chip sum is the output projection's, which XLA emits as
  one psum over ``tp``.
- FFN ``wi [d, d_ff]`` shards ``d_ff`` over ``tp`` (column), ``wo [d_ff, d]``
  shards ``d_ff`` over ``tp`` (row) — same single-psum property.
- Embedding/vocab tables shard the vocab dim over ``tp`` (output projection is
  a matmul against the transpose, so logits arrive vocab-sharded and the
  argmax/softmax runs sharded too).
- LayerNorm scales/biases and position tables replicate (tiny).
- Activations: batch over ``dp``, sequence over ``sp`` (ring attention's
  layout, SURVEY.md §5.7).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


def _attn_specs() -> Params:
    return {
        "wq": P(None, "tp", None),
        "wk": P(None, "tp", None),
        "wv": P(None, "tp", None),
        "wo": P("tp", None, None),
    }


def _dense_specs(col: bool) -> Params:
    # init_dense produces {"w": [in, out], "b": [out]}.
    if col:
        return {"w": P(None, "tp"), "b": P("tp")}
    return {"w": P("tp", None), "b": P()}


def _ln_specs() -> Params:
    return {"scale": P(), "bias": P()}


def _block_specs(cross: bool = False) -> Params:
    p: Params = {
        "ln1": _ln_specs(),
        "attn": _attn_specs(),
        "ln2": _ln_specs(),
        "ffn": {"wi": _dense_specs(col=True), "wo": _dense_specs(col=False)},
    }
    if cross:
        p["ln_x"] = _ln_specs()
        p["xattn"] = _attn_specs()
    return p


def _moe_block_specs() -> Params:
    """Block with a Switch MoE FFN — the moe subtree's specs come from the
    ONE definition in ``models.moe`` so the two trees cannot diverge."""
    from agent_tpu.models.moe import moe_param_specs

    return {
        "ln1": _ln_specs(),
        "attn": _attn_specs(),
        "ln2": _ln_specs(),
        "moe": moe_param_specs(),
    }


def encoder_param_specs(cfg) -> Params:
    """PartitionSpec pytree matching ``models.encoder.init_params(cfg)``."""
    moe = getattr(cfg, "moe_experts", 0) > 0
    return {
        "embed": P("tp", None),
        "pos": P(),
        "blocks": [
            _moe_block_specs() if moe else _block_specs()
            for _ in range(cfg.n_layers)
        ],
        "ln_f": _ln_specs(),
        "head": _dense_specs(col=True),
    }


def bert_param_specs(cfg) -> Params:
    """PartitionSpec pytree matching ``models.bert.from_state_dict``.

    Same Megatron column/row pattern as the in-house encoder: q/k/v and the
    FFN input are column-parallel, the output projections row-parallel (one
    psum per block), vocab-dim sharding for the embedding table; LayerNorms
    and the small pooler/head replicate their biases per ``_dense_specs``.
    """
    blk = {
        "attn": {
            "q": _dense_specs(col=True),
            "k": _dense_specs(col=True),
            "v": _dense_specs(col=True),
            "o": _dense_specs(col=False),
            "ln": _ln_specs(),
        },
        "ffn": {
            "i": _dense_specs(col=True),
            "o": _dense_specs(col=False),
            "ln": _ln_specs(),
        },
    }
    return {
        "embed": {
            "word": P("tp", None),
            "pos": P(),
            "type": P(),
            "ln": _ln_specs(),
        },
        "layers": [dict(blk) for _ in range(cfg.num_layers)],
        "pooler": _dense_specs(col=True),
        "head": _dense_specs(col=False),
    }


def seq2seq_param_specs(cfg) -> Params:
    """PartitionSpec pytree matching ``models.seq2seq.init_params(cfg)``."""
    return {
        "embed": P("tp", None),
        "pos": P(),
        "enc": [_block_specs() for _ in range(cfg.n_enc_layers)],
        "dec": [_block_specs(cross=True) for _ in range(cfg.n_dec_layers)],
        "ln_enc": _ln_specs(),
        "ln_dec": _ln_specs(),
    }


def t5_param_specs(cfg) -> Params:
    """PartitionSpec pytree matching ``models.t5.from_state_dict`` — T5's
    bias-free linears are bare [in, out] leaves: q/k/v and the FFN inputs
    column-parallel, output projections row-parallel; RMSNorm scales and the
    tiny relative-bias tables replicate; vocab-dim sharding for the
    embedding (and untied lm_head)."""
    col, row = P(None, "tp"), P("tp", None)

    def attn():
        return {"q": col, "k": col, "v": col, "o": row}

    def blk(cross: bool):
        ffn = (
            {"wi_0": col, "wi_1": col, "wo": row}
            if cfg.gated_ffn else {"wi": col, "wo": row}
        )
        p: Params = {"attn": attn(), "ln1": P(), "ffn": ffn, "ln2": P()}
        if cross:
            p["cross"] = attn()
            p["ln_x"] = P()
        return p

    def branch(n: int, cross: bool):
        return {
            "rel_bias": P(),
            "layers": [blk(cross) for _ in range(n)],
            "ln_f": P(),
        }

    out: Params = {
        "embed": P("tp", None),
        "enc": branch(cfg.n_enc_layers, cross=False),
        "dec": branch(cfg.n_dec_layers, cross=True),
    }
    if not cfg.tie_word_embeddings:
        out["lm_head"] = P(None, "tp")
    return out


def bart_param_specs(cfg) -> Params:
    """PartitionSpec pytree matching ``models.bart.from_state_dict`` — the
    same column/row pattern as :func:`bert_param_specs`, vocab-dim sharding
    for the tied embedding/lm-head table."""

    def attn():
        return {
            "q": _dense_specs(col=True),
            "k": _dense_specs(col=True),
            "v": _dense_specs(col=True),
            "o": _dense_specs(col=False),
        }

    def blk(cross: bool):
        p: Params = {
            "self": attn(),
            "ln1": _ln_specs(),
            "fc1": _dense_specs(col=True),
            "fc2": _dense_specs(col=False),
            "ln2": _ln_specs(),
        }
        if cross:
            p["cross"] = attn()
            p["ln_x"] = _ln_specs()
        return p

    def branch(n: int, cross: bool):
        return {
            "pos": P(),
            "ln_emb": _ln_specs(),
            "layers": [blk(cross) for _ in range(n)],
        }

    return {
        "embed": P("tp", None),
        "final_logits_bias": P(),
        "enc": branch(cfg.n_enc_layers, cross=False),
        "dec": branch(cfg.n_dec_layers, cross=True),
    }


def _axes_size(mesh, entry) -> int:
    """Mesh extent of one PartitionSpec entry (name or tuple of names)."""
    if entry is None:
        return 1
    names = entry if isinstance(entry, tuple) else (entry,)
    size = 1
    for n in names:
        size *= mesh.shape.get(n, 1)
    return size


def sanitize_specs(mesh, params: Any, specs: Any) -> Any:
    """Per-leaf divisibility guard: any leaf whose sharded dims don't divide
    the mesh axes gets a replicated ``P()`` instead.

    Lets one spec pytree serve every model config — e.g. a payload
    ``model_config`` with 6 heads on a tp=4 mesh serves with that projection
    replicated rather than failing the op.
    """

    def drop_missing(entry):
        # Axis names the mesh doesn't have (e.g. "ep" on a dp/tp mesh)
        # would make NamedSharding raise; such entries replicate instead.
        if entry is None:
            return None
        names = entry if isinstance(entry, tuple) else (entry,)
        kept = tuple(n for n in names if n in mesh.shape)
        if not kept:
            return None
        return kept if isinstance(entry, tuple) else kept[0]

    def fix(leaf, spec):
        shape = getattr(leaf, "shape", ())
        if len(spec) > len(shape):
            return P()
        spec = P(*(drop_missing(e) for e in spec))
        for dim, entry in zip(shape, spec):
            if dim % _axes_size(mesh, entry) != 0:
                return P()
        return spec

    return jax.tree_util.tree_map(
        fix, params, specs, is_leaf=lambda x: isinstance(x, P)
    )


def batch_spec() -> P:
    """[B, L] token batches: batch over dp, sequence over sp."""
    return P("dp", "sp")


def label_spec() -> P:
    """[B] labels: batch over dp."""
    return P("dp")
