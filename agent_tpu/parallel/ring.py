"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference handled long inputs by truncation only (1,024-token cap on
summarize, reference ``ops/map_summarize.py:49``; 2,048-token profile limit,
reference ``app.py:108``). The TPU-native upgrade (SURVEY.md §5.7): shard the
*sequence* axis over ``sp`` so context length scales with chips instead of
hitting one chip's HBM wall.

Mechanics (blockwise attention with a ``lax.ppermute`` ring, scaling-book
recipe): every device holds one block of Q rows and one block of K/V rows.
Each of the ``sp`` steps computes attention of the local Q block against the
currently-held K/V block while folding results into a streaming (flash-style)
softmax — running row max ``m``, running denominator ``l``, running numerator
``acc`` — then rotates the K/V block (and its key-padding mask slice) one hop
around the ring. After ``sp`` hops every Q block has seen every K/V block and
the blocks are home again. Communication is neighbor-to-neighbor only, which
is exactly what TPU ICI rings are built for; compute on block *i* overlaps
XLA-scheduled transfer of block *i+1*.

Scope: key-padding masks only (``[B, 1, 1, Lk]`` — encoder self-attention and
cross-attention). Causal decode doesn't meet this path: decode queries one
position against a full KV cache (``models/seq2seq._decode_step``), where
sequence sharding buys nothing.

Drop-in contract: :func:`make_ring_attention` returns a function with the
``attn_fn`` signature of ``agent_tpu.models.layers.attention``; shapes that
don't divide the mesh (or non-key-only masks) silently take the dense path,
so callers never need a compatibility check.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from agent_tpu.models.layers import NEG_INF, dot_product_attention
from agent_tpu.utils.compat import pcast_varying, shard_map


def _ring_local(q, k, v, mask, sp: int, use_flash_fold: bool = False):
    """Per-device body: streaming-softmax attention over ``sp`` ring hops.

    q: [b, h, lq, d] (local Q block, f32-scaled below)
    k, v: [b, h, lk, d] (current K/V block, rotates)
    mask: [b, 1, 1, lk] key-padding block (1 = attend, rotates with K/V)

    With ``use_flash_fold`` each hop's fold runs as the fused Pallas kernel
    (``agent_tpu.kernels.flash_attention.flash_fold``) instead of einsums —
    the ring schedules communication, the kernel does the math, closing the
    sp>1-bypasses-the-kernel gap.
    """
    out_dtype = q.dtype
    scale = 1.0 / np.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32) * scale

    if use_flash_fold:
        from agent_tpu.kernels.flash_attention import (
            flash_fold,
            flash_fold_supported,
        )

        use_flash_fold = flash_fold_supported(q.shape, k.shape[2])

    b, h, lq, _ = q.shape
    # Mark the zero-init carry device-varying: shard_map requires the scan
    # carry's manual-axes type to match its (varying) outputs. (No-op on
    # pre-vma jax — see compat.pcast_varying.)
    varying = partial(pcast_varying, axis_name=("dp", "tp", "sp"))
    m0 = varying(jnp.full((b, h, lq, 1), NEG_INF, dtype=jnp.float32))
    l0 = varying(jnp.zeros((b, h, lq, 1), dtype=jnp.float32))
    acc0 = varying(jnp.zeros(q.shape, dtype=jnp.float32))
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def fold(k_blk, v_blk, m_blk, m, l, acc):
        """Fold one K/V block into the streaming softmax state.

        Same m/l/acc update as the Pallas flash kernel's per-tile fold
        (``agent_tpu.kernels.flash_attention._flash_fold_kernel``) — a
        numerics change there must land here too; the einsum form is the
        fallback when the kernel path is off or the shapes don't tile.
        """
        if use_flash_fold:
            return flash_fold(
                q, k_blk, v_blk, m_blk, m, l, acc,
                vma=frozenset({"dp", "tp", "sp"}),
            )
        scores = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32)
        )
        scores = jnp.where(m_blk > 0, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        # Masked entries must contribute exactly 0 even when the whole block
        # is masked (scores == m_new == NEG_INF would make exp() == 1).
        p = jnp.exp(scores - m_new) * (m_blk > 0)
        correction = jnp.exp(m - m_new)
        l = l * correction + p.sum(axis=-1, keepdims=True)
        acc = acc * correction + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32)
        )
        return m_new, l, acc

    # Local block first, then rotate-and-fold sp-1 times: a uniform
    # fold-then-rotate scan would pay one extra (discarded) K/V rotation.
    m, l, acc = fold(k, v, mask, m0, l0, acc0)

    def hop(carry, _):
        k_blk, v_blk, m_blk, m, l, acc = carry
        k_blk = lax.ppermute(k_blk, "sp", perm)
        v_blk = lax.ppermute(v_blk, "sp", perm)
        m_blk = lax.ppermute(m_blk, "sp", perm)
        m, l, acc = fold(k_blk, v_blk, m_blk, m, l, acc)
        return (k_blk, v_blk, m_blk, m, l, acc), None

    (_, _, _, _, l, acc), _ = lax.scan(
        hop, (k, v, mask, m, l, acc), None, length=sp - 1
    )
    # Fully-padded rows have l == 0 (all-pad batch-bucket rows): emit 0, not NaN.
    return (acc / jnp.maximum(l, 1e-30)).astype(out_dtype)


def make_ring_attention(mesh: Mesh, use_flash_fold: bool = None):
    """``attn_fn`` running ring attention over ``mesh``'s ``sp`` axis.

    With ``sp == 1`` (or shapes/mask the ring can't take) this is exactly
    :func:`~agent_tpu.models.layers.dot_product_attention` — same program,
    different mesh, preserving the framework's one-codepath rule
    (SURVEY.md §7: fallback is a backend/mesh switch, not a second model).

    ``use_flash_fold`` (default: auto — on for real TPU) runs each hop's
    local fold as the fused Pallas kernel.
    """
    shape = dict(mesh.shape)
    sp = shape.get("sp", 1)
    if sp <= 1:
        return dot_product_attention
    dp = shape.get("dp", 1)
    tp = shape.get("tp", 1)
    if use_flash_fold is None:
        use_flash_fold = jax.default_backend() == "tpu"

    sharded = shard_map(
        partial(_ring_local, sp=sp, use_flash_fold=use_flash_fold),
        mesh=mesh,
        in_specs=(
            P("dp", "tp", "sp", None),   # q: heads over tp, Lq over sp
            P("dp", "tp", "sp", None),   # k: Lk over sp (ring-rotated)
            P("dp", "tp", "sp", None),   # v
            P("dp", None, None, "sp"),   # key-padding mask: Lk over sp
        ),
        out_specs=P("dp", "tp", "sp", None),
        # The pallas INTERPRET-mode lowering emits dynamic_slices whose
        # operands confuse the vma checker inside shard_map (jax suggests
        # exactly this workaround). Scoped to interpret mode only: compiled
        # TPU runs keep full varying-mesh-axes verification (the fold's
        # outputs carry their vma annotation).
        check_vma=not (use_flash_fold and jax.default_backend() != "tpu"),
    )

    def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                       mask: jax.Array) -> jax.Array:
        from agent_tpu.models.layers import (
            is_key_padding_mask,
            materialize_key_padding_mask,
        )

        B, H, Lq, _ = q.shape
        Lk = k.shape[2]
        ring_ok = (
            is_key_padding_mask(mask, B, Lk)
            and B % dp == 0
            and H % tp == 0
            and Lq % sp == 0
            and Lk % sp == 0
        )
        if not ring_ok:
            return dot_product_attention(q, k, v, mask)
        return sharded(q, k, v, materialize_key_padding_mask(mask, B, Lk))

    return ring_attention
