"""On-device reductions over the mesh data axis.

``mesh_reduce_stats`` is the device path of ``risk_accumulate`` (BASELINE.json
north star: "risk_accumulate runs as an on-device lax.psum reduction",
replacing the reference's host-side ``sum``/``min``/``max``, reference
``ops/risk_accumulate.py:65-68``): values are sharded over ``dp``, each shard
reduces locally on its chip, and the partials combine over ICI with
``lax.psum``/``pmin``/``pmax`` inside a ``shard_map``.

Shape discipline: input length is padded up to a power-of-two multiple of the
dp axis size with a mask, so the executable cache sees a small set of static
lengths (same bucketing story as ``pad_batch``).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _padded_len(n: int, multiple: int) -> int:
    """Smallest power-of-two bucket ≥ n that is a multiple of ``multiple``."""
    size = max(multiple, 1)
    while size < n:
        size *= 2
    return size


def _build_stats_fn(runtime) -> Any:
    mesh = runtime.mesh

    def local_stats(x: jax.Array, m: jax.Array):
        s = lax.psum(jnp.sum(x * m), "dp")
        mn = lax.pmin(jnp.min(jnp.where(m > 0, x, jnp.inf)), "dp")
        mx = lax.pmax(jnp.max(jnp.where(m > 0, x, -jnp.inf)), "dp")
        return s, mn, mx

    fn = jax.shard_map(
        local_stats,
        mesh=mesh,
        in_specs=(P("dp"), P("dp")),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(fn)


def mesh_reduce_stats(runtime, values: Sequence[float]) -> Dict[str, Any]:
    """count/sum/mean/min/max of ``values``, reduced on-device over ``dp``.

    Returns the ``risk_accumulate`` result fields (reference
    ``ops/risk_accumulate.py:70-77`` shape); the caller adds ``ok``/timing.
    """
    n = len(values)
    if n == 0:
        return {"count": 0, "sum": 0.0, "mean": 0.0, "min": None, "max": None}
    dp = runtime.axis_size("dp")
    size = _padded_len(n, dp)
    x = np.zeros(size, dtype=np.float32)
    x[:n] = np.asarray(values, dtype=np.float32)
    m = np.zeros(size, dtype=np.float32)
    m[:n] = 1.0

    fn = runtime.compiled(
        ("mesh_reduce_stats", size, dp), lambda: _build_stats_fn(runtime)
    )
    sharding = runtime.sharding("dp")
    s, mn, mx = fn(jax.device_put(x, sharding), jax.device_put(m, sharding))
    # count is exact host knowledge (len), not a float32 mask-psum: a mask sum
    # loses integer exactness past 2^24 elements.
    total = float(s)
    return {
        "count": n,
        "sum": total,
        "mean": total / n,
        "min": float(mn),
        "max": float(mx),
    }
