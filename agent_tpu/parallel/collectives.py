"""On-device reductions over the mesh data axis.

``mesh_reduce_stats`` is the device path of ``risk_accumulate`` (BASELINE.json
north star: "risk_accumulate runs as an on-device lax.psum reduction",
replacing the reference's host-side ``sum``/``min``/``max``, reference
``ops/risk_accumulate.py:65-68``): values are sharded over ``dp``, each shard
reduces locally on its chip, and the partials combine over ICI with
``lax.psum``/``pmin``/``pmax`` inside a ``shard_map``.

Shape discipline: input length is padded up to a power-of-two multiple of the
dp axis size with a mask, so the executable cache sees a small set of static
lengths (same bucketing story as ``pad_batch``).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from agent_tpu.utils.compat import shard_map


def _padded_len(n: int, multiple: int) -> int:
    """Smallest power-of-two bucket ≥ n that is a multiple of ``multiple``."""
    size = max(multiple, 1)
    while size < n:
        size *= 2
    return size


def _build_stats_fn(runtime) -> Any:
    mesh = runtime.mesh

    def local_stats(hi: jax.Array, lo: jax.Array, m: jax.Array):
        # Double-single sum: hi/lo are the f32 split of the f64 inputs (hi =
        # round(v), lo = v - hi), so the sum of BOTH partial sums recovers the
        # f64 values' sum up to f32 *accumulation* error — the input-cast
        # error of a plain f32 path is gone entirely. The two partials
        # combine on the host in f64 (see mesh_reduce_stats).
        s_hi = lax.psum(jnp.sum(hi * m), "dp")
        s_lo = lax.psum(jnp.sum(lo * m), "dp")
        # min/max via monotone bitcast keys, reduced as *integers*.  A float
        # pmin/pmax on the VPU flushes subnormal inputs to zero (FTZ), which
        # broke the exact-f32 contract for inputs like 1.4e-45 (round-4
        # Hypothesis counterexample).  The IEEE-754 sign-magnitude encoding
        # admits a monotone map to uint32 — key = bits ^ (0x80000000 for
        # positives, 0xFFFFFFFF for negatives) — so integer reductions order
        # floats exactly, subnormals included: bitcast, xor, and integer
        # min/max never touch the float datapath, so nothing can flush.
        bits = lax.bitcast_convert_type(hi, jnp.uint32)
        key = bits ^ jnp.where(
            (bits >> 31) != 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0x80000000)
        )
        # Pad sentinels: 0xFFFFFFFF is the largest key (above +inf's), 0 the
        # smallest (below -inf's); n ≥ 1 guarantees a real element survives.
        k_mn = lax.pmin(
            jnp.min(jnp.where(m > 0, key, jnp.uint32(0xFFFFFFFF))), "dp"
        )
        k_mx = lax.pmax(jnp.max(jnp.where(m > 0, key, jnp.uint32(0))), "dp")
        return s_hi, s_lo, k_mn, k_mx

    fn = shard_map(
        local_stats,
        mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P(), P(), P(), P()),
    )
    return jax.jit(fn)


def mesh_reduce_stats(runtime, values: Sequence[float]) -> Dict[str, Any]:
    """count/sum/mean/min/max of ``values``, reduced on-device over ``dp``.

    Returns the ``risk_accumulate`` result fields (reference
    ``ops/risk_accumulate.py:70-77`` shape); the caller adds ``ok``/timing.

    Numerics contract: inputs ship as a double-single (hi/lo f32) pair, so
    there is NO input-cast error vs the host ``math.fsum`` path for the
    **sum** (the residual is f32 *accumulation* error of the shard-local
    sums, worst-case relative ``n · 2⁻²⁴`` and in practice far smaller — XLA
    reduces in trees). **min/max equal the f32 rounding of the exact f64
    extremes — an equality, not a tolerance, subnormals included**: rounding
    is monotone, so ``min(round(v)) == round(min(v))``, and the reduction
    runs over monotone bitcast integer keys (see ``_build_stats_fn``) so the
    device's flush-to-zero float mode cannot perturb it. The controller-side
    merge path stays exact (``risk_accumulate`` host fsum); the sum here
    trades the last-ulp accumulation exactness for on-chip reduction over
    ICI.
    """
    n = len(values)
    if n == 0:
        return {"count": 0, "sum": 0.0, "mean": 0.0, "min": None, "max": None}
    if np.isnan(values).any():
        # NaN poisons every statistic, deterministically. Without this check
        # the bitcast-key reduce would apply IEEE total-order semantics
        # (negative NaN < -inf, positive NaN > +inf) — order-independent but
        # asymmetric (min skips a positive NaN that max returns) — and the
        # host path's Python ``min``/``max`` are order-DEPENDENT under NaN,
        # so neither is a contract worth matching. ``fsum`` already yields
        # NaN for the sum; min/max follow it. (Same canonicalization in the
        # ``risk_accumulate`` host path.)
        nan = float("nan")
        return {"count": n, "sum": nan, "mean": nan, "min": nan, "max": nan}
    dp = runtime.axis_size("dp")
    size = _padded_len(n, dp)
    v64 = np.zeros(size, dtype=np.float64)
    v64[:n] = np.asarray(values, dtype=np.float64)
    # Values beyond f32 range cast to ±inf; their residual would be ∓inf and
    # the recombined sum inf + -inf = NaN. Zero the residual instead so the
    # overflow stays a detectable inf, same as a plain f32 cast. Both the
    # overflowing cast and the inf arithmetic are this function's documented
    # behavior, not accidents — silence numpy's warnings for exactly that.
    with np.errstate(over="ignore", invalid="ignore"):
        hi = v64.astype(np.float32)
        lo = np.where(
            np.isfinite(hi), v64 - hi.astype(np.float64), 0.0
        ).astype(np.float32)
    m = np.zeros(size, dtype=np.float32)
    m[:n] = 1.0

    fn = runtime.compiled(
        ("mesh_reduce_stats", size, dp), lambda: _build_stats_fn(runtime)
    )
    sharding = runtime.sharding("dp")
    s_hi, s_lo, k_mn, k_mx = fn(
        jax.device_put(hi, sharding),
        jax.device_put(lo, sharding),
        jax.device_put(m, sharding),
    )
    # count is exact host knowledge (len), not a float32 mask-psum: a mask sum
    # loses integer exactness past 2^24 elements. The hi/lo partials combine
    # here in f64 — the whole point of shipping the split.
    total = float(s_hi) + float(s_lo)
    return {
        "count": n,
        "sum": total,
        "mean": total / n,
        "min": _key_to_f32(int(k_mn)),
        "max": _key_to_f32(int(k_mx)),
    }


def _key_to_f32(key: int) -> float:
    """Invert the monotone uint32 order key back to its f32 value (host side,
    pure integer ops — the device never reconstructs the float)."""
    bits = key ^ (0x80000000 if key & 0x80000000 else 0xFFFFFFFF)
    return float(np.uint32(bits).view(np.float32))
