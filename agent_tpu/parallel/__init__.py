"""Parallelism: shardings, collectives, and sequence parallelism.

The reference had exactly one form of parallelism — controller-side task
sharding over HTTP (SURVEY.md §2.8); its "reduce" was host Python ``sum``/``min``
/``max`` (reference ``ops/risk_accumulate.py:65-68``) combined controller-side.
This package supplies the intra-pod tier that did not exist: XLA collectives
over the mesh's ICI links (``lax.psum``/``pmin``/``pmax`` in
:mod:`~agent_tpu.parallel.collectives`, ring ``ppermute`` attention in
:mod:`~agent_tpu.parallel.ring`). The HTTP tier remains the DCN outer
loop (SURVEY.md §5.8 two-tier design).
"""

from agent_tpu.parallel.collectives import mesh_reduce_stats
from agent_tpu.parallel.pipeline import encoder_forward_pp, pipeline_blocks
from agent_tpu.parallel.ring import make_ring_attention

__all__ = [
    "mesh_reduce_stats",
    "make_ring_attention",
    "encoder_forward_pp",
    "pipeline_blocks",
]
