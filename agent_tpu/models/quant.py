"""INT8 quantized execution (W8A8, dynamic activation scales).

The reference's entire device story was INT8: the Edge-TPU ran an
INT8-compiled TFLite artifact with an int8 input contract (reference
``ops/map_classify_tpu.py:53,58-69``, ``ops/_tpu_runtime.py:23-31``, the
Coral toolchain in ``Dockerfile:9-30``). The TPU-native successor is not a
quantized *artifact* but a quantized *execution mode*: the same checkpoint /
deterministic params, with the hot matmuls running ``int8 × int8 → int32``
on the MXU — ~2× the bf16 MXU rate on v5e — and dequantizing into the f32
residual stream. Serving contract, tokenization, and result shapes are
unchanged; ``model_config: {"quant": "int8"}`` (or ``TPU_QUANT=int8``)
flips the mode per task.

Scheme (the standard dynamic W8A8 recipe, AQT-style but hand-rolled):

- **Weights**: symmetric per-output-channel int8, quantized once at build
  time on the host (``w_q = round(w / s)``, ``s = amax/127`` over the
  contracting axes). Host-side quantization also shrinks the host→HBM
  transfer 4× vs f32 leaves.
- **Activations**: symmetric per-row dynamic int8 at trace time — abs-max
  over the contracting axes, fused by XLA into the preceding elementwise op
  (LN / GELU). No calibration pass, no clipping tuning.
- **Matmul**: ``lax.dot_general(x_q, w_q, preferred_element_type=int32)``;
  the int32 product dequantizes as ``y · s_x · s_w`` in f32.
- **What stays high-precision**: embeddings, LayerNorms, softmax, residual
  adds, the attention score/context matmuls (QKᵀ, PV — both activations,
  dynamic-range-fragile), and the tiny classifier/pooler heads. FFN + QKVO
  projections carry ~90% of encoder FLOPs, bounding the ideal speedup near
  1.8×.

Leaf convention: a quantized projection replaces the f32 array (or
``{"w", "b"}`` dense dict) with ``{"w_q": int8, "w_scale": f32[out-dims]}``
(+ ``"b"``). ``layers.dense`` / ``layers.attention`` / the model-local dense
helpers dispatch on that structure, so every family (encoder, BERT, BART,
T5) serves quantized through its unmodified forward.

A second execution mode, **W8A16 weight-only** (``quant: "w8a16"``), keeps
the same int8 weight tables but leaves activations in the compute dtype —
no dynamic quantization pass at all. Its leaf convention is ``{"w8": int8,
"w_scale"}`` (+ ``"b"``), and the same dispatch sites route it through
:func:`wdense` / :func:`wproj_in` / :func:`wproj_out` / :func:`wmoe_expert`.
W8A8 is the big-matmul *encoder* mode (MXU rate); W8A16 is the thin-matmul
*decode* mode (HBM weight bandwidth) — see the section comments below.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]

_QMAX = 127.0
# Floor for dynamic scales: an all-zero row would otherwise divide by zero.
# 1e-8/127 keeps true zeros exact (0/s = 0) without NaN.
_EPS = 1e-8


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "w_q" in leaf


def is_weight_only(leaf: Any) -> bool:
    """W8A16 leaf (``{"w8": int8, "w_scale"}``): int8 weight table, but
    activations stay in the compute dtype — no dynamic quantization."""
    return isinstance(leaf, dict) and "w8" in leaf


# ---- weight quantization (host, build-time) ----


def quantize_weight(w: Any, reduce_axes: Tuple[int, ...]) -> Params:
    """Symmetric per-channel int8: scale over the contracting ``reduce_axes``.

    Runs on host numpy (``np.asarray`` fetches device leaves once) so the
    int8 table — not the f32 original — is what ships to HBM.
    """
    w = np.asarray(w, dtype=np.float32)
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.maximum(amax, _EPS) / _QMAX
    w_q = np.clip(np.rint(w / scale), -_QMAX, _QMAX).astype(np.int8)
    return {
        "w_q": w_q,
        "w_scale": np.squeeze(scale, axis=reduce_axes).astype(np.float32),
    }


def quantize_dense(p: Params) -> Params:
    """``{"w": [in, out], "b"}`` → ``{"w_q", "w_scale": [out], "b"}``."""
    out = quantize_weight(p["w"], (0,))
    out["b"] = np.asarray(p["b"], dtype=np.float32)
    return out


def quantize_weight_w8a16(w: Any, reduce_axes: Tuple[int, ...]) -> Params:
    """W8A16 twin of :func:`quantize_weight`: the SAME int8 table and scale,
    stored under the weight-only leaf key ``w8`` so the dispatch sites pick
    the activation-passthrough matmuls instead of the W8A8 ones."""
    q = quantize_weight(w, reduce_axes)
    return {"w8": q["w_q"], "w_scale": q["w_scale"]}


def quantize_dense_w8a16(p: Params) -> Params:
    """``{"w": [in, out], "b"}`` → ``{"w8", "w_scale": [out], "b"}``."""
    out = quantize_weight_w8a16(p["w"], (0,))
    out["b"] = np.asarray(p["b"], dtype=np.float32)
    return out


# ---- activation quantization (device, trace-time) ----


def quantize_act(x: jax.Array, axes: Tuple[int, ...] = (-1,)):
    """Dynamic symmetric int8 over ``axes`` → (x_q int8, scale f32 keepdims).

    The abs-max reduce runs in the *input* dtype (bf16 on TPU) so no f32
    copy of the activation ever materializes — the quantize chain is two
    fused passes over x (reduce, then scale/round/cast). The clip stays:
    a bf16-rounded amax can undershoot the true max by up to 2⁻⁸ relative,
    putting |x/s| at ~127.5 in the worst case.
    """
    amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True).astype(jnp.float32)
    scale = jnp.maximum(amax, _EPS) / _QMAX
    x_q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -_QMAX, _QMAX
    ).astype(jnp.int8)
    return x_q, scale


# ---- quantized matmuls ----
#
# These stay on XLA's ``dot_general(int8, int8 → int32)`` ON PURPOSE. A
# Pallas W8A8 kernel with the dequant epilogue fused in VMEM (int32 never
# reaching HBM) was built and measured end to end at BERT-base serving
# shapes on v5e (batch 4096, seq 512): bf16 1,136 rows/s, XLA int8 1,333,
# Pallas kernel 587 — the ``pallas_call`` fusion barrier (activation
# quantization can no longer fuse into the preceding LN/GELU) plus the
# blocked re-reads of x per N-tile cost far more than the epilogue saves.
#
# Why the end-to-end win is ~1.2×, not the spec sheet's 2× — the measured
# decomposition (v5e, calibrated chained-loop windows; the end-to-end
# speedup and agreement are the recorded ``bert_base_int8`` bench leg,
# BENCH_r05: 1.272× at top-1 agreement 1.0):
#   - the int8 dot itself DOES run at ~2.0× the bf16 MXU rate
#     (353-365 TOP/s vs 175-183 TF/s at MXU-saturating shapes);
#   - the dequant epilogue is FREE — XLA fuses int32→f32·sx·sw+b into the
#     dot's output pass (dot+epilogue == bare dot, 1.72 vs 1.75 ms at the
#     BERT FFN shape);
#   - dynamic activation quantization costs the one remaining overhead
#     (~27% on a bare FFN matmul; partly amortized in-model where the amax
#     pass fuses with the producing LN/GELU, and the identical Q/K/V
#     quantizations CSE to one — verified in compiled HLO);
#   - Amdahl does the rest: 40.6% of the bf16 forward is non-matmul
#     elementwise/HBM traffic (LN, GELU, softmax, residuals — matmul-floor
#     ablation) and the attention score/context matmuls stay bf16 by
#     choice, so quantizing the projections+FFN at a true 2× bounds the
#     whole forward near ~1.35×; measured 1.16-1.22×.
# A ≥1.5× serving speedup therefore needs a smaller elementwise share
# (fused attention at seq 512, activation-dtype changes), not a faster
# int8 matmul — the matmul is already double-rate.


def qdense(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    """int8 path of ``layers.dense``: x [..., in] @ w [in, out] + b."""
    x_q, sx = quantize_act(x)                       # sx [..., 1]
    y = lax.dot_general(
        x_q, p["w_q"],
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32)
    y = y * (sx * p["w_scale"])                     # [..., out]
    if "b" in p:
        y = y + p["b"]
    return y.astype(dtype)


def qproj_in(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    """int8 path of the head-axis input projection:
    x [B, L, d] @ w [d, H, E] → [B, H, L, E] (the ``bld,dhe->bhle`` einsum)."""
    x_q, sx = quantize_act(x)                       # sx [B, L, 1]
    y = lax.dot_general(
        x_q, p["w_q"],
        (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32)                           # [B, L, H, E]
    y = y * (sx[..., None] * p["w_scale"][None, None])
    return y.astype(dtype).transpose(0, 2, 1, 3)


def qproj_out(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    """int8 path of the head-axis output projection:
    x [B, H, L, E] @ w [H, E, d] → [B, L, d] (the ``bhle,hed->bld`` einsum)."""
    xt = x.transpose(0, 2, 1, 3)                    # [B, L, H, E]
    x_q, sx = quantize_act(xt, axes=(2, 3))         # sx [B, L, 1, 1]
    y = lax.dot_general(
        x_q, p["w_q"],
        (((2, 3), (0, 1)), ((), ())),
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32)                           # [B, L, d]
    y = y * (sx[..., 0] * p["w_scale"])
    return y.astype(dtype)


# ---- weight-only (W8A16) matmuls ----
#
# The memory-bound recipe for DECODE: the per-step matmuls are [rows, d]-thin
# (rows ≤ batch, d = d_model), so the MXU is idle waiting on HBM and the
# W8A8 activation-quant overhead buys nothing (measured: 3,983 int8 vs
# 4,980 bf16 rows/s at B=1024 — bench.py decode note). Weight-only keeps
# activations in the compute dtype and ships/reads the int8 table (half the
# bf16 bytes, a quarter of f32), dequantizing by a per-output-channel scale
# on the dot's OUTPUT — the epilogue fuses, and there is no quantize pass
# at all. Same int8 tables as W8A8 (quantize_weight), different execution.


def wdense(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    """W8A16 path of ``layers.dense``: x [..., in] @ w8 [in, out] + b."""
    y = jnp.dot(x.astype(dtype), p["w8"].astype(dtype))
    y = y.astype(jnp.float32) * p["w_scale"]
    if "b" in p:
        y = y + p["b"]
    return y.astype(dtype)


def wproj_in(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    """W8A16 path of the head-axis input projection:
    x [B, L, d] @ w8 [d, H, E] → [B, H, L, E]."""
    y = lax.dot_general(
        x.astype(dtype), p["w8"].astype(dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
    )                                               # [B, L, H, E]
    y = (y.astype(jnp.float32) * p["w_scale"][None, None]).astype(dtype)
    return y.transpose(0, 2, 1, 3)


def wproj_out(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    """W8A16 path of the head-axis output projection:
    x [B, H, L, E] @ w8 [H, E, d] → [B, L, d]."""
    xt = x.transpose(0, 2, 1, 3)                    # [B, L, H, E]
    y = lax.dot_general(
        xt, p["w8"].astype(dtype),
        (((2, 3), (0, 1)), ((), ())),
    )                                               # [B, L, d]
    return (y.astype(jnp.float32) * p["w_scale"]).astype(dtype)


def wmoe_expert(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    """W8A16 path of the grouped expert matmul (layout as
    :func:`qmoe_expert`): x [G, E, C, d_in] @ w8 [E, d_in, d_out]."""
    y = lax.dot_general(
        x.astype(dtype), p["w8"].astype(dtype),
        (((3,), (1,)), ((1,), (0,))),               # contract d; batch E
    )                                               # [E, G, C, d_out]
    y = y.transpose(1, 0, 2, 3).astype(jnp.float32) \
        * p["w_scale"][None, :, None, :]
    return y.astype(dtype)


def qmoe_expert(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    """int8 path of the grouped expert matmul (``models.moe``):
    x [G, E, C, d_in] @ w [E, d_in, d_out] → [G, E, C, d_out] (the
    ``gecd,edf->gecf`` / ``gecf,efd->gecd`` einsums, expert dim batched).

    Per-slot dynamic activation scales (each [g, e, c] capacity row
    quantizes over its feature axis) and per-expert-per-channel weight
    scales (``quantize_weight(w, (1,))`` → [E, d_out]), so each expert's
    matmul is the same W8A8 recipe as :func:`qdense`. Capacity-padding rows
    are all-zero → scale floors at ``_EPS`` → exact zeros, same as dense."""
    x_q, sx = quantize_act(x)                       # sx [G, E, C, 1]
    y = lax.dot_general(
        x_q, p["w_q"],
        (((3,), (1,)), ((1,), (0,))),               # contract d; batch E
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32)                           # [E, G, C, d_out]
    y = y.transpose(1, 0, 2, 3) * (sx * p["w_scale"][None, :, None, :])
    return y.astype(dtype)


# ---- family param-tree transformers (+ matching spec transformers) ----
#
# Each quantize_* below has a *_specs twin transforming the same paths of the
# shardings.* spec tree; they live side by side so the structures cannot
# drift. Scale specs keep the non-contracted entries of the weight spec
# (e.g. wq [d, H, E] P(None, "tp", None) → scale [H, E] P("tp", None)).
#
# Every transformer is parameterized by ``mode`` ("int8" W8A8 / "w8a16"
# weight-only): the two modes quantize the SAME tree paths with the SAME
# reduce axes and differ only in the leaf convention (``w_q`` vs ``w8``),
# so one traversal serves both and the modes cannot drift structurally.


def _qw_spec(spec: P, reduce_axes: Sequence[int], wkey: str = "w_q") -> Params:
    keep = [s for i, s in enumerate(spec) if i not in reduce_axes]
    return {wkey: spec, "w_scale": P(*keep)}


def _qdense_spec(spec: Params, wkey: str = "w_q") -> Params:
    out = _qw_spec(spec["w"], (0,), wkey)
    out["b"] = spec["b"]
    return out


# mode → (weight quantizer, dense quantizer, weight-spec fn, dense-spec fn).
_MODES = {
    "int8": (
        quantize_weight,
        quantize_dense,
        lambda s, ax: _qw_spec(s, ax, "w_q"),
        lambda s: _qdense_spec(s, "w_q"),
    ),
    "w8a16": (
        quantize_weight_w8a16,
        quantize_dense_w8a16,
        lambda s, ax: _qw_spec(s, ax, "w8"),
        lambda s: _qdense_spec(s, "w8"),
    ),
}


def _quantize_attn(a: Params, mode: str = "int8") -> Params:
    qw = _MODES[mode][0]
    return {
        "wq": qw(a["wq"], (0,)),
        "wk": qw(a["wk"], (0,)),
        "wv": qw(a["wv"], (0,)),
        "wo": qw(a["wo"], (0, 1)),
    }


def _quantize_attn_specs(a: Params, mode: str = "int8") -> Params:
    ws = _MODES[mode][2]
    return {
        "wq": ws(a["wq"], (0,)),
        "wk": ws(a["wk"], (0,)),
        "wv": ws(a["wv"], (0,)),
        "wo": ws(a["wo"], (0, 1)),
    }


def _quantize_block(b: Params, mode: str = "int8") -> Params:
    qw, qd = _MODES[mode][0], _MODES[mode][1]
    nb = dict(b)
    nb["attn"] = _quantize_attn(b["attn"], mode)
    if "ffn" in b:
        nb["ffn"] = {
            "wi": qd(b["ffn"]["wi"]),
            "wo": qd(b["ffn"]["wo"]),
        }
    if "moe" in b:
        # Switch MoE FFN: expert-stacked weights take per-expert-per-channel
        # int8 (scale over each expert's contracting dim); the router stays
        # f32 — it is tiny and its softmax/argmax routing decisions are
        # dynamic-range-fragile (same exclusion rule as attention scores).
        m = b["moe"]
        nb["moe"] = {
            "router": m["router"],
            "wi": qw(m["wi"], (1,)),
            "wo": qw(m["wo"], (1,)),
        }
    if "xattn" in b:
        nb["xattn"] = _quantize_attn(b["xattn"], mode)
    return nb


def _quantize_block_specs(b: Params, mode: str = "int8") -> Params:
    ws = _MODES[mode][2]
    ds = _MODES[mode][3]
    nb = dict(b)
    nb["attn"] = _quantize_attn_specs(b["attn"], mode)
    if "ffn" in b:
        nb["ffn"] = {
            "wi": ds(b["ffn"]["wi"]),
            "wo": ds(b["ffn"]["wo"]),
        }
    if "moe" in b:
        m = b["moe"]
        nb["moe"] = {
            "router": m["router"],
            "wi": ws(m["wi"], (1,)),   # scale [E, d_out] → P("ep", ·)
            "wo": ws(m["wo"], (1,)),
        }
    if "xattn" in b:
        nb["xattn"] = _quantize_attn_specs(b["xattn"], mode)
    return nb


def quantize_encoder(params: Params, mode: str = "int8") -> Params:
    """In-house encoder tree (``models.encoder.init_params``): quantize every
    block's QKVO + FFN; embeddings, LNs, and the head stay f32."""
    out = dict(params)
    out["blocks"] = [_quantize_block(b, mode) for b in params["blocks"]]
    return out


def quantize_encoder_specs(specs: Params, mode: str = "int8") -> Params:
    out = dict(specs)
    out["blocks"] = [_quantize_block_specs(b, mode) for b in specs["blocks"]]
    return out


def quantize_bert(params: Params, mode: str = "int8") -> Params:
    """HF-BERT tree (``models.bert.from_state_dict``): per-layer QKVO + FFN
    dense dicts; embeddings, LNs, pooler, and head stay f32."""
    qd = _MODES[mode][1]
    out = dict(params)
    out["layers"] = []
    for blk in params["layers"]:
        a, f = blk["attn"], blk["ffn"]
        out["layers"].append({
            "attn": {
                "q": qd(a["q"]),
                "k": qd(a["k"]),
                "v": qd(a["v"]),
                "o": qd(a["o"]),
                "ln": a["ln"],
            },
            "ffn": {
                "i": qd(f["i"]),
                "o": qd(f["o"]),
                "ln": f["ln"],
            },
        })
    return out


def quantize_bert_specs(specs: Params, mode: str = "int8") -> Params:
    ds = _MODES[mode][3]
    out = dict(specs)
    out["layers"] = []
    for blk in specs["layers"]:
        a, f = blk["attn"], blk["ffn"]
        out["layers"].append({
            "attn": {
                "q": ds(a["q"]),
                "k": ds(a["k"]),
                "v": ds(a["v"]),
                "o": ds(a["o"]),
                "ln": a["ln"],
            },
            "ffn": {
                "i": ds(f["i"]),
                "o": ds(f["o"]),
                "ln": f["ln"],
            },
        })
    return out


def quantize_seq2seq(params: Params, mode: str = "int8") -> Params:
    """In-house seq2seq tree (``models.seq2seq.init_params``): quantize every
    encoder/decoder block (incl. cross-attention); embeddings and final LNs
    stay f32 (the lm head is the tied embedding — unquantized)."""
    out = dict(params)
    out["enc"] = [_quantize_block(b, mode) for b in params["enc"]]
    out["dec"] = [_quantize_block(b, mode) for b in params["dec"]]
    return out


def quantize_seq2seq_specs(specs: Params, mode: str = "int8") -> Params:
    out = dict(specs)
    out["enc"] = [_quantize_block_specs(b, mode) for b in specs["enc"]]
    out["dec"] = [_quantize_block_specs(b, mode) for b in specs["dec"]]
    return out


def _quantize_bart_block(blk: Params, mode: str = "int8") -> Params:
    qd = _MODES[mode][1]
    nb = dict(blk)
    nb["self"] = {k: qd(v) for k, v in blk["self"].items()}
    if "cross" in blk:
        nb["cross"] = {k: qd(v) for k, v in blk["cross"].items()}
    nb["fc1"] = qd(blk["fc1"])
    nb["fc2"] = qd(blk["fc2"])
    return nb


def _quantize_bart_block_specs(blk: Params, mode: str = "int8") -> Params:
    ds = _MODES[mode][3]
    nb = dict(blk)
    nb["self"] = {k: ds(v) for k, v in blk["self"].items()}
    if "cross" in blk:
        nb["cross"] = {k: ds(v) for k, v in blk["cross"].items()}
    nb["fc1"] = ds(blk["fc1"])
    nb["fc2"] = ds(blk["fc2"])
    return nb


def quantize_bart(params: Params, mode: str = "int8") -> Params:
    """HF-BART tree (``models.bart.from_state_dict``): QKVO + FFN dense dicts
    per layer; embeddings / position tables / LNs / final_logits_bias stay
    f32 (the lm head is the tied embedding)."""
    out = dict(params)
    for branch in ("enc", "dec"):
        br = dict(params[branch])
        br["layers"] = [
            _quantize_bart_block(b, mode) for b in params[branch]["layers"]
        ]
        out[branch] = br
    return out


def quantize_bart_specs(specs: Params, mode: str = "int8") -> Params:
    out = dict(specs)
    for branch in ("enc", "dec"):
        br = dict(specs[branch])
        br["layers"] = [
            _quantize_bart_block_specs(b, mode)
            for b in specs[branch]["layers"]
        ]
        out[branch] = br
    return out


def _quantize_t5_block(blk: Params, mode: str = "int8") -> Params:
    qw = _MODES[mode][0]
    nb = dict(blk)
    nb["attn"] = {
        k: qw(w, (0,)) for k, w in blk["attn"].items()
    }
    if "cross" in blk:
        nb["cross"] = {
            k: qw(w, (0,)) for k, w in blk["cross"].items()
        }
    nb["ffn"] = {
        k: qw(w, (0,)) for k, w in blk["ffn"].items()
    }
    return nb


def _quantize_t5_block_specs(blk: Params, mode: str = "int8") -> Params:
    ws = _MODES[mode][2]
    nb = dict(blk)
    nb["attn"] = {k: ws(s, (0,)) for k, s in blk["attn"].items()}
    if "cross" in blk:
        nb["cross"] = {k: ws(s, (0,)) for k, s in blk["cross"].items()}
    nb["ffn"] = {k: ws(s, (0,)) for k, s in blk["ffn"].items()}
    return nb


def quantize_t5(params: Params, mode: str = "int8") -> Params:
    """HF-T5 tree (``models.t5.from_state_dict``): bias-free QKVO + FFN bare
    matrices per layer; embeddings, RMSNorm scales, relative-bias tables, and
    the (possibly untied) lm head stay f32."""
    out = dict(params)
    for branch in ("enc", "dec"):
        br = dict(params[branch])
        br["layers"] = [
            _quantize_t5_block(b, mode) for b in params[branch]["layers"]
        ]
        out[branch] = br
    return out


def quantize_t5_specs(specs: Params, mode: str = "int8") -> Params:
    out = dict(specs)
    for branch in ("enc", "dec"):
        br = dict(specs[branch])
        br["layers"] = [
            _quantize_t5_block_specs(b, mode)
            for b in specs[branch]["layers"]
        ]
        out[branch] = br
    return out


# Family name (the ops' model-family strings) → (params, specs) transformer
# pair. Single dispatch point so the two model ops cannot drift (the same
# anti-drift rule as ops/_model_common.py).
_FAMILY_QUANTIZERS = {
    "encoder": lambda: (quantize_encoder, quantize_encoder_specs),
    "bert": lambda: (quantize_bert, quantize_bert_specs),
    "seq2seq": lambda: (quantize_seq2seq, quantize_seq2seq_specs),
    "bart": lambda: (quantize_bart, quantize_bart_specs),
    "t5": lambda: (quantize_t5, quantize_t5_specs),
}


def quantize_for_family(family: str, params: Params,
                        mode: str = "int8") -> Params:
    return _FAMILY_QUANTIZERS[family]()[0](params, mode)


def quantize_specs_for_family(family: str, specs: Params,
                              mode: str = "int8") -> Params:
    return _FAMILY_QUANTIZERS[family]()[1](specs, mode)


# quant values that trigger the build-time tree transform (everything but
# "none"); _model_common.maybe_quantize_params gates on membership here so a
# new mode needs exactly one registration (this tuple + _MODES).
QUANTIZED_MODES = ("int8", "w8a16")
VALID_QUANT = ("none",) + QUANTIZED_MODES


def validate_quant(value: str) -> str:
    """Payload/env ``quant`` value → validated, or ValueError (soft error)."""
    if value not in VALID_QUANT:
        raise ValueError(
            f"quant must be one of {VALID_QUANT}, got {value!r}"
        )
    return value
