"""Model families and tokenizers.

The reference executed models through third-party stacks (INT8 TFLite on an Edge
TPU, reference ``ops/map_classify_tpu.py``; torch BART on host CPU, reference
``ops/map_summarize.py``). Here every model is a pure-JAX param-dict function
(deliberately not Flax: pytrees of arrays shard/checkpoint/transform with zero
framework indirection) compiled with ``jax.jit``/``pjit`` over the mesh, and
tokenization is in-repo (no hub downloads — the framework must run with zero
egress).

Submodules import lazily; importing ``agent_tpu.models`` does not pull in JAX.
"""
