"""GPT-2-style byte-level BPE — the tokenizer family of BART/RoBERTa
checkpoints (``vocab.json`` + ``merges.txt``).

Implements the exact algorithm of the reference tokenizers (byte→unicode
remap, regex pre-tokenization, greedy lowest-rank merges) so ids match
``transformers``' slow GPT2/BART tokenizer token for token — differential
tested in ``tests/test_bart.py``. Pure Python + ``regex``; no network, no
tokenizers-library dependency.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from functools import lru_cache
from typing import Dict, List, Tuple

import regex as re

# GPT-2's pre-tokenization pattern, verbatim.
_PAT = re.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
)

# Loaded-tokenizer cache: LRU-bounded (a drain cycling vocab_path payloads
# must not grow host memory without bound) and keyed by file mtimes so an
# edited vocab/merges pair reloads instead of serving stale.
_DIR_CACHE_MAX = 8
_dir_cache: "OrderedDict[tuple, ByteLevelBPE]" = OrderedDict()
_dir_cache_lock = threading.Lock()


@lru_cache(maxsize=1)
def bytes_to_unicode() -> Dict[int, str]:
    """The GPT-2 reversible byte→printable-unicode table."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


class ByteLevelBPE:
    """Encoder/decoder over a GPT-2 vocab.json + merges.txt pair."""

    def __init__(self, vocab: Dict[str, int],
                 merges: List[Tuple[str, str]]) -> None:
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {c: b for b, c in self.byte_encoder.items()}
        self._cache: Dict[str, List[str]] = {}
        self._cache_lock = threading.Lock()

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @classmethod
    def from_dir(cls, path: str) -> "ByteLevelBPE":
        """Load (and cache) the tokenizer for a vocab directory. Cached per
        (absolute path, file mtimes): real vocabs are ~50k entries and the
        BPE merge cache only pays off if callers share one instance (both
        ``map_tokenize`` and the BART serving path load through here). The
        cache holds at most ``_DIR_CACHE_MAX`` tokenizers (LRU) and an
        edited vocab/merges pair reloads on the next call.

        Malformed inputs raise ValueError (callers' soft-error class) — a
        non-dict vocab.json must not escape as an AttributeError later.
        """
        vocab_path = os.path.join(path, "vocab.json")
        merges_path = os.path.join(path, "merges.txt")
        key = (
            os.path.abspath(path),
            os.path.getmtime(vocab_path),
            os.path.getmtime(merges_path),
        )
        with _dir_cache_lock:
            hit = _dir_cache.get(key)
            if hit is not None:
                _dir_cache.move_to_end(key)
                return hit
        with open(vocab_path, encoding="utf-8") as f:
            vocab = json.load(f)
        if not isinstance(vocab, dict):
            raise ValueError(
                f"vocab.json must hold a token->id object, got "
                f"{type(vocab).__name__}"
            )
        merges: List[Tuple[str, str]] = []
        with open(merges_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        tok = cls(vocab, merges)
        with _dir_cache_lock:
            _dir_cache[key] = tok
            _dir_cache.move_to_end(key)
            while len(_dir_cache) > _DIR_CACHE_MAX:
                _dir_cache.popitem(last=False)
        return tok

    def _bpe(self, token: str) -> List[str]:
        with self._cache_lock:
            hit = self._cache.get(token)
        if hit is not None:
            return hit
        word = list(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            first, second = best
            merged: List[str] = []
            i = 0
            while i < len(word):
                if (
                    i < len(word) - 1
                    and word[i] == first
                    and word[i + 1] == second
                ):
                    merged.append(first + second)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged
        with self._cache_lock:
            if len(self._cache) < 65536:  # bound drain-scale memory
                self._cache[token] = word
        return word

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for tok in _PAT.findall(text):
            mapped = "".join(
                self.byte_encoder[b] for b in tok.encode("utf-8")
            )
            ids.extend(self.vocab[piece] for piece in self._bpe(mapped))
        return ids

    def decode(self, ids) -> str:
        text = "".join(self.inv_vocab.get(int(i), "") for i in ids)
        raw = bytes(
            self.byte_decoder[c] for c in text if c in self.byte_decoder
        )
        return raw.decode("utf-8", errors="replace")
