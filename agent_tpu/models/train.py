"""Sharded training step for the encoder classifier.

The reference never trains (inference-only Edge TPU agent); training exists in
the new framework because a TPU-native model op needs a way to *produce* the
``.npz`` checkpoints the ops load (``encoder.load_npz``), and because the
multi-chip path must be exercised end to end — forward, loss, backward,
optimizer — under one jit over the full ``(dp, tp, sp)`` mesh.

Pattern: params are placed with :mod:`agent_tpu.parallel.shardings` specs,
batches with ``P('dp', 'sp')``, and the whole step is one ``jax.jit`` with
``donate_argnums`` on (params, opt_state) — XLA inserts the tp psums for the
matmuls and the dp/sp gradient all-reduces; no hand-written collectives.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from agent_tpu.models import encoder, layers
from agent_tpu.parallel import shardings


# Switch Transformer's load-balance coefficient (α, Switch §2.2): small
# enough not to fight the task loss, large enough to keep routing uniform.
MOE_AUX_WEIGHT = 0.01


def cross_entropy_loss(
    params, ids: jax.Array, mask: jax.Array, labels: jax.Array, cfg,
    remat: bool = False, attn_fn=None,
) -> jax.Array:
    """Mean NLL; MoE configs add the Switch load-balancing aux loss
    (α=0.01) — training a router WITHOUT it collapses routing onto one
    expert (capacity-dropped tokens pass through with zero FFN output and
    the imbalance is self-reinforcing)."""
    attn_fn = attn_fn or layers.dot_product_attention
    moe = getattr(cfg, "moe_experts", 0) > 0
    out = encoder.forward(params, ids, mask, cfg, remat=remat,
                          attn_fn=attn_fn, with_aux=moe)
    logits, aux = out if moe else (out, None)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    loss = nll.mean()
    if moe:
        loss = loss + MOE_AUX_WEIGHT * aux
    return loss


def make_train_step(cfg, optimizer=None, remat: bool = False, attn_fn=None):
    """Build ``(init_state, step)`` where ``step`` is one jitted SGD update.

    ``init_state(params)`` → opt_state; ``step(params, opt_state, ids, mask,
    labels)`` → (params, opt_state, loss). Shard placement is the caller's.

    **Contract: ``step`` DONATES its (params, opt_state) arguments** — the
    input buffers are invalidated and must be replaced with the returned
    pair (every in-repo caller reassigns). Reusing the old pytrees after a
    step raises "Array has been deleted"; pass explicit copies if you need
    to step the same params twice.

    ``remat=True`` rematerializes each encoder block in the backward pass
    (``jax.checkpoint``) — required at BERT-base scale, where stored
    attention scores alone exceed one chip's HBM (see ``encoder.forward``).

    ``attn_fn`` must be DIFFERENTIABLE end to end — pass
    ``kernels.flash_attention_trainable`` (or the mesh wrapper from
    ``runtime.train_attention_fn()``), never the forward-only inference
    kernel, whose ``pallas_call`` has no AD rule. Default: dense attention.
    """
    optimizer = optimizer or optax.adamw(1e-3)

    def init_state(params):
        return optimizer.init(params)

    # Donation: the caller always replaces (params, opt_state) with the
    # returned pair, so XLA may update weights in place — without it the
    # step holds two copies of params + optimizer state in HBM.
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, ids, mask, labels):
        loss, grads = jax.value_and_grad(cross_entropy_loss)(
            params, ids, mask, labels, cfg, remat, attn_fn
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return init_state, step


def place_sharded(runtime, params, specs) -> Any:
    """Place a host param pytree onto the mesh per a PartitionSpec pytree."""
    mesh = runtime.mesh

    def put(leaf, spec):
        return jax.device_put(leaf, jax.sharding.NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(
        put, params, specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    )


def train_step_sharded(runtime, cfg, batch_size: int, seq_len: int,
                       attn_fn=None):
    """One full sharded training step on synthetic data; returns the loss.

    This is the multi-chip proof path (`__graft_entry__.dryrun_multichip`):
    params sharded per ``encoder_param_specs`` (tp), batch per ``P(dp, sp)``,
    one jitted fwd+bwd+update executed on the runtime's mesh.

    ``attn_fn=None`` selects via ``runtime.train_attention_fn()`` — the
    differentiable flash kernel on TPU at ≥``FLASH_TRAIN_MIN_KEY_LEN``
    (512 — the training gate sits below serving's 2048, see the gate note
    in ``kernels/flash_attention.py``), dense otherwise.
    """
    mesh = runtime.mesh
    params = encoder.init_params(cfg, model_id="train-dryrun")
    specs = shardings.encoder_param_specs(cfg)
    params = place_sharded(runtime, params, specs)

    init_state, step = make_train_step(
        cfg, attn_fn=attn_fn or runtime.train_attention_fn()
    )
    opt_state = init_state(params)

    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (batch_size, seq_len), 0, cfg.vocab_size)
    mask = jnp.ones((batch_size, seq_len), dtype=jnp.int32)
    labels = jax.random.randint(rng, (batch_size,), 0, cfg.n_classes)

    bspec = jax.sharding.NamedSharding(mesh, shardings.batch_spec())
    lspec = jax.sharding.NamedSharding(mesh, shardings.label_spec())
    ids = jax.device_put(ids.astype(jnp.int32), bspec)
    mask = jax.device_put(mask, bspec)
    labels = jax.device_put(labels.astype(jnp.int32), lspec)

    params, opt_state, loss = step(params, opt_state, ids, mask, labels)
    jax.block_until_ready(loss)
    return float(loss)
