"""HF-T5-compatible seq2seq: the checkpoint family BASELINE.json names for
the summarize slot ("map_summarize.py T5-large seq2seq").

Faithful to ``transformers``' T5: RMSNorm (no mean subtraction, no bias),
pre-LN residual blocks, **relative position biases** (bucketed, learned in
the first block of each stack and shared by the rest, bidirectional for the
encoder / causal for the decoder), unscaled attention (the 1/√d is folded
into T5's init), ReLU or gated-GELU FFN per ``feed_forward_proj``, and a
lm_head tied to the embedding with the ``d_model**-0.5`` output scale (or an
untied head when the checkpoint has one). Differential-tested against
``transformers`` (logits and generated tokens) in ``tests/test_t5.py``.

Generation runs on the shared scan engines (``models/decoding.py``) with KV
caches; the decoder's causal relative bias is precomputed for the static
decode length and sliced per step.

Serving text through ``map_summarize`` additionally needs the checkpoint's
SentencePiece tokenizer: gated on the ``sentencepiece`` package
(:func:`hf_spm`), with a clear error when absent — the model/ids path works
without it.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from agent_tpu.models.layers import NEG_INF, Params


@dataclass(frozen=True)
class T5Config:
    """Mirror of the HF T5 ``config.json`` fields the forward needs."""

    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64              # per-head dim (decoupled from d_model in T5)
    n_heads: int = 8
    n_enc_layers: int = 6
    n_dec_layers: int = 6
    d_ff: int = 2048
    rel_buckets: int = 32
    rel_max_distance: int = 128
    gated_ffn: bool = False     # v1.1 "gated-gelu"; v1.0 is plain relu
    tie_word_embeddings: bool = True
    pad_id: int = 0
    eos_id: int = 1
    decoder_start_id: int = 0   # T5 starts decode from pad
    layer_norm_eps: float = 1e-6
    dtype: str = "bfloat16"
    # "int8": serve with W8A8 quantized matmuls (models.quant); "w8a16":
    # weight-only int8 — the decode-mode recipe (int8-resident weights
    # dequantized in-register, activations stay at dtype).
    quant: str = "none"

    # Uniform serving-config view (map_summarize reads these off any family).
    # T5 has no position table — length is bounded by memory, not params;
    # 1024 mirrors the reference's input truncation.
    max_src_len: int = 1024
    max_tgt_len: int = 1024

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def from_hf_json(cls, path: str, **overrides) -> "T5Config":
        try:
            with open(path) as f:
                hf = json.load(f)
        except json.JSONDecodeError as exc:
            raise RuntimeError(
                f"unreadable checkpoint config.json at {path}: {exc}"
            ) from exc
        if hf.get("model_type") not in (None, "t5"):
            raise RuntimeError(
                f"not a T5 checkpoint (model_type={hf.get('model_type')!r})"
            )
        proj = hf.get("feed_forward_proj", "relu")
        # Whitelist, don't approximate: a 'gelu' or 'gated-silu' checkpoint
        # served through the wrong activation would return ok=true with wrong
        # numerics — fail loudly as a retryable integrity error instead (same
        # contract as the model_type check above).
        if proj not in ("relu", "gated-gelu"):
            raise RuntimeError(
                f"unsupported T5 feed_forward_proj={proj!r} "
                "(supported: 'relu', 'gated-gelu')"
            )
        fields = dict(
            vocab_size=hf["vocab_size"],
            d_model=hf["d_model"],
            d_kv=hf["d_kv"],
            n_heads=hf["num_heads"],
            n_enc_layers=hf["num_layers"],
            n_dec_layers=hf.get("num_decoder_layers", hf["num_layers"]),
            d_ff=hf["d_ff"],
            rel_buckets=hf.get("relative_attention_num_buckets", 32),
            rel_max_distance=hf.get("relative_attention_max_distance", 128),
            gated_ffn=proj.startswith("gated"),
            tie_word_embeddings=hf.get("tie_word_embeddings", True),
            pad_id=hf.get("pad_token_id", 0),
            eos_id=hf.get("eos_token_id", 1),
            decoder_start_id=hf.get(
                "decoder_start_token_id", hf.get("pad_token_id", 0)
            ),
            layer_norm_eps=hf.get("layer_norm_epsilon", 1e-6),
        )
        fields.update(overrides)
        return cls(**fields)


def _rms(p: jax.Array, x: jax.Array, eps: float) -> jax.Array:
    """T5LayerNorm: scale / rms, no mean subtraction, no bias; f32 stats."""
    x32 = x.astype(jnp.float32)
    var = (x32 * x32).mean(axis=-1, keepdims=True)
    return (p * (x32 * jax.lax.rsqrt(var + eps))).astype(x.dtype)


def _dense(w: jax.Array, x: jax.Array, dtype) -> jax.Array:
    """Bias-free linear (T5 has no biases anywhere); w is [in, out]."""
    from agent_tpu.models import quant

    if quant.is_quantized(w):  # int8 leaf (models.quant convention)
        return quant.qdense(w, x, dtype)
    if quant.is_weight_only(w):  # W8A16 leaf: decode-mode weight-only int8
        return quant.wdense(w, x, dtype)
    return jnp.dot(x.astype(dtype), w.astype(dtype))


def relative_position_bucket(
    relative_position: jax.Array, bidirectional: bool,
    num_buckets: int, max_distance: int,
) -> jax.Array:
    """HF ``_relative_position_bucket``, verbatim semantics.

    ``relative_position`` = key_pos − query_pos (any int array).
    """
    rel = relative_position
    bucket = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        bucket = bucket + (rel > 0).astype(rel.dtype) * num_buckets
        rel = jnp.abs(rel)
    else:
        rel = -jnp.minimum(rel, 0)
    max_exact = num_buckets // 2
    is_small = rel < max_exact
    rel_f = jnp.maximum(rel.astype(jnp.float32), 1.0)
    large = max_exact + (
        jnp.log(rel_f / max_exact) / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(rel.dtype)
    large = jnp.minimum(large, num_buckets - 1)
    return bucket + jnp.where(is_small, rel, large)


def _position_bias(
    rel_bias: jax.Array,       # [num_buckets, H]
    q_pos: jax.Array,          # [Lq] int32 absolute query positions
    k_pos: jax.Array,          # [Lk] int32 absolute key positions
    bidirectional: bool,
    cfg: T5Config,
) -> jax.Array:
    """[1, H, Lq, Lk] additive attention bias (f32)."""
    rel = k_pos[None, :] - q_pos[:, None]                  # [Lq, Lk]
    buckets = relative_position_bucket(
        rel, bidirectional, cfg.rel_buckets, cfg.rel_max_distance
    )
    bias = rel_bias.astype(jnp.float32)[buckets]           # [Lq, Lk, H]
    return bias.transpose(2, 0, 1)[None]                   # [1, H, Lq, Lk]


def _attn(blk: Params, q_in, kv_in, bias, cfg, *, Lq: int, Lk: int):
    """T5 attention: UNSCALED scores + additive ``bias`` (position bias and
    padding mask pre-combined, f32), softmax in f32. blk = {q, k, v, o}."""
    dtype = cfg.compute_dtype
    B = q_in.shape[0]

    def heads(t, L):
        return t.reshape(B, L, cfg.n_heads, cfg.d_kv).transpose(0, 2, 1, 3)

    q = heads(_dense(blk["q"], q_in, dtype), Lq)
    k = heads(_dense(blk["k"], kv_in, dtype), Lk)
    v = heads(_dense(blk["v"], kv_in, dtype), Lk)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) + bias
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, Lq, cfg.n_heads * cfg.d_kv)
    return _dense(blk["o"], ctx, dtype)


def _ffn(blk: Params, x, cfg) -> jax.Array:
    dtype = cfg.compute_dtype
    if cfg.gated_ffn:
        h = jax.nn.gelu(
            _dense(blk["wi_0"], x, dtype).astype(jnp.float32),
            approximate=True,  # HF gated-gelu uses the tanh approximation
        ).astype(dtype) * _dense(blk["wi_1"], x, dtype)
    else:
        h = jax.nn.relu(_dense(blk["wi"], x, dtype))
    return _dense(blk["wo"], h, cfg.compute_dtype)


def _pad_bias(mask: jax.Array) -> jax.Array:
    """[B, Lk] padding mask → additive [B, 1, 1, Lk] f32 bias."""
    return jnp.where(mask[:, None, None, :] > 0, 0.0, NEG_INF).astype(
        jnp.float32
    )


def encode(params: Params, src_ids: jax.Array, src_mask: jax.Array,
           cfg: T5Config, use_flash: Optional[bool] = None,
           kernel=None) -> jax.Array:
    """Encoder stack → [B, Ls, d].

    Long-context path: self-attention routes through the fused Pallas T5
    kernel, which computes the bucketed relative-position bias per tile in
    VMEM instead of materializing the [H, Ls, Ls] bias in HBM. ``kernel``
    lets the caller pass a mesh-aware wrapper
    (``kernels.make_flash_attention_t5(mesh)`` — batch over dp, heads over
    tp); with ``kernel=None``, ``use_flash`` (default: auto — single-chip
    TPU traces only, since bare ``pallas_call`` has no GSPMD partitioning
    rule) selects the plain kernel. Either declines unsupported shapes at
    trace time (returns None) and the layer falls back to the dense path
    with a lazily built dense bias; kernel == dense is asserted in tests.
    """
    dtype = cfg.compute_dtype
    B, L = src_ids.shape
    if kernel is None:
        if use_flash is None:
            use_flash = (
                jax.default_backend() == "tpu" and jax.device_count() == 1
            )
        if use_flash:
            from agent_tpu.kernels.flash_attention import flash_attention_t5

            kernel = flash_attention_t5
    x = jnp.asarray(params["embed"]).astype(dtype)[src_ids]
    rel_bias = jnp.asarray(params["enc"]["rel_bias"])
    mask4 = src_mask[:, None, None, :].astype(jnp.int32)
    dense_bias = None  # built only when the dense path is taken

    def heads(t):
        return t.reshape(B, L, cfg.n_heads, cfg.d_kv).transpose(0, 2, 1, 3)

    for i, blk in enumerate(params["enc"]["layers"]):
        h = _rms(blk["ln1"], x, cfg.layer_norm_eps)
        a = blk["attn"]
        q = heads(_dense(a["q"], h, dtype))
        k = heads(_dense(a["k"], h, dtype))
        v = heads(_dense(a["v"], h, dtype))
        ctx = None
        if kernel is not None:
            ctx = kernel(
                q, k, v, mask4, rel_bias,
                bidirectional=True, max_distance=cfg.rel_max_distance,
                scale=1.0,
            )
            if i == 0 and ctx is None:
                # The gate is shape-static and identical for every layer:
                # decide once so fallback traces don't re-attempt per layer
                # (and the selection counter ticks once per program).
                kernel = None
        if ctx is None:
            if dense_bias is None:
                pos = jnp.arange(L, dtype=jnp.int32)
                dense_bias = _position_bias(
                    rel_bias, pos, pos, True, cfg
                ) + _pad_bias(src_mask)
            # Dense path on the SAME q/k/v (T5: unscaled scores + bias).
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(
                jnp.float32
            ) + dense_bias
            probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, L, cfg.n_heads * cfg.d_kv)
        x = x + _dense(a["o"], ctx, dtype)
        h = _rms(blk["ln2"], x, cfg.layer_norm_eps)
        x = x + _ffn(blk["ffn"], h, cfg)
    return _rms(params["enc"]["ln_f"], x, cfg.layer_norm_eps)


def _lm_logits(params: Params, x: jax.Array, cfg: T5Config) -> jax.Array:
    dtype = cfg.compute_dtype
    if cfg.tie_word_embeddings:
        x = x * (cfg.d_model ** -0.5)
        w = jnp.asarray(params["embed"]).astype(dtype).T
    else:
        w = jnp.asarray(params["lm_head"]).astype(dtype)
    return jnp.dot(x.astype(dtype), w).astype(jnp.float32)


def decode_full(params: Params, tgt_ids: jax.Array, enc_out: jax.Array,
                enc_mask: jax.Array, cfg: T5Config) -> jax.Array:
    """Teacher-forced decoder → lm logits [B, Lt, V] — the differential-test
    surface vs HF ``T5ForConditionalGeneration`` logits."""
    dtype = cfg.compute_dtype
    B, Lt = tgt_ids.shape
    Ls = enc_out.shape[1]
    x = jnp.asarray(params["embed"]).astype(dtype)[tgt_ids]
    pos = jnp.arange(Lt, dtype=jnp.int32)
    causal = jnp.where(
        pos[None, :] <= pos[:, None], 0.0, NEG_INF
    ).astype(jnp.float32)[None, None]
    self_bias = _position_bias(
        params["dec"]["rel_bias"], pos, pos, False, cfg
    ) + causal
    cross_bias = _pad_bias(enc_mask)  # no positional bias on cross-attn
    for blk in params["dec"]["layers"]:
        h = _rms(blk["ln1"], x, cfg.layer_norm_eps)
        x = x + _attn(blk["attn"], h, h, self_bias, cfg, Lq=Lt, Lk=Lt)
        h = _rms(blk["ln_x"], x, cfg.layer_norm_eps)
        x = x + _attn(blk["cross"], h, enc_out, cross_bias, cfg,
                      Lq=Lt, Lk=Ls)
        h = _rms(blk["ln2"], x, cfg.layer_norm_eps)
        x = x + _ffn(blk["ffn"], h, cfg)
    x = _rms(params["dec"]["ln_f"], x, cfg.layer_norm_eps)
    return _lm_logits(params, x, cfg)


# ---- cached single-step decode (generation) ----


def _init_self_caches(cfg: T5Config, batch: int, max_new: int) -> list:
    dtype = cfg.compute_dtype
    return [
        {
            "k": jnp.zeros((batch, cfg.n_heads, max_new, cfg.d_kv), dtype=dtype),
            "v": jnp.zeros((batch, cfg.n_heads, max_new, cfg.d_kv), dtype=dtype),
        }
        for _ in range(cfg.n_dec_layers)
    ]


def _init_cross_kv(params: Params, enc_out: jax.Array, cfg: T5Config) -> list:
    """Cross-attention K/V computed once (loop-invariant; closed over by the
    step function, NOT carried through the scan — see models/bart.py)."""
    B, Ls, _ = enc_out.shape
    dtype = cfg.compute_dtype

    def heads(t):
        return t.reshape(B, Ls, cfg.n_heads, cfg.d_kv).transpose(0, 2, 1, 3)

    return [
        {
            "k": heads(_dense(blk["cross"]["k"], enc_out, dtype)),
            "v": heads(_dense(blk["cross"]["v"], enc_out, dtype)),
        }
        for blk in params["dec"]["layers"]
    ]


def decode_step(params: Params, tok: jax.Array, step: jax.Array,
                self_caches: list, cross_kv: list, dec_bias: jax.Array,
                enc_mask_bias: jax.Array, cfg: T5Config,
                max_new: int) -> Tuple[jax.Array, list]:
    """One cached decoder step → (logits [B, V] f32, self_caches).

    ``dec_bias`` is the precomputed causal relative bias [1, H, T, T] for the
    static decode length; row ``step`` is sliced per step."""
    dtype = cfg.compute_dtype
    B = tok.shape[0]
    x = jnp.asarray(params["embed"]).astype(dtype)[tok][:, None]  # [B, 1, d]
    # [1, H, 1, T]: this step's row of the causal+relative bias. Positions
    # > step already carry NEG_INF from the causal term.
    bias_row = jax.lax.dynamic_slice_in_dim(dec_bias, step, 1, axis=2)
    new_self = []
    for blk, s_kv, x_kv in zip(
        params["dec"]["layers"], self_caches, cross_kv
    ):
        h = _rms(blk["ln1"], x, cfg.layer_norm_eps)
        a = blk["attn"]
        q = _dense(a["q"], h, dtype).reshape(B, 1, cfg.n_heads, cfg.d_kv)
        q = q.transpose(0, 2, 1, 3)
        k1 = _dense(a["k"], h, dtype).reshape(B, 1, cfg.n_heads, cfg.d_kv)
        v1 = _dense(a["v"], h, dtype).reshape(B, 1, cfg.n_heads, cfg.d_kv)
        k = jax.lax.dynamic_update_slice(
            s_kv["k"], k1.transpose(0, 2, 1, 3), (0, 0, step, 0)
        )
        v = jax.lax.dynamic_update_slice(
            s_kv["v"], v1.transpose(0, 2, 1, 3), (0, 0, step, 0)
        )
        new_self.append({"k": k, "v": v})
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        probs = jax.nn.softmax(scores + bias_row, axis=-1).astype(dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, 1, cfg.n_heads * cfg.d_kv)
        x = x + _dense(a["o"], ctx, dtype)

        h = _rms(blk["ln_x"], x, cfg.layer_norm_eps)
        c = blk["cross"]
        qx = _dense(c["q"], h, dtype).reshape(B, 1, cfg.n_heads, cfg.d_kv)
        qx = qx.transpose(0, 2, 1, 3)
        xs = jnp.einsum("bhqd,bhkd->bhqk", qx, x_kv["k"]).astype(jnp.float32)
        xp = jax.nn.softmax(xs + enc_mask_bias, axis=-1).astype(dtype)
        cctx = jnp.einsum("bhqk,bhkd->bhqd", xp, x_kv["v"])
        cctx = cctx.transpose(0, 2, 1, 3).reshape(
            B, 1, cfg.n_heads * cfg.d_kv
        )
        x = x + _dense(c["o"], cctx, dtype)

        h = _rms(blk["ln2"], x, cfg.layer_norm_eps)
        x = x + _ffn(blk["ffn"], h, cfg)
    x = _rms(params["dec"]["ln_f"], x, cfg.layer_norm_eps)
    return _lm_logits(params, x, cfg)[:, 0], new_self


def generate(
    params: Params,
    src_ids: jax.Array,
    src_mask: jax.Array,
    cfg: T5Config,
    max_new_tokens: int,
    num_beams: int = 1,
    length_penalty: float = 1.0,
    early_stopping: bool = False,
    min_length: int = 0,
    kernel=None,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy (or beam) generation via the shared scan engines. Returns
    (tokens [B, T], lengths [B]); tokens after EOS are the pad id.

    ``kernel`` routes the encoder pass through a fused T5 attention kernel
    (see :func:`encode` — pass ``runtime.t5_attention_kernel()`` for the
    mesh-aware wrapper); the decoder's incremental steps keep the dense
    bias path (per-step Lq == 1 is outside the kernel's contract)."""
    from agent_tpu.models.decoding import beam_scan, greedy_scan

    B = src_ids.shape[0]
    T = max_new_tokens
    enc_out = encode(params, src_ids, src_mask, cfg, kernel=kernel)
    pos = jnp.arange(T, dtype=jnp.int32)
    causal = jnp.where(
        pos[None, :] <= pos[:, None], 0.0, NEG_INF
    ).astype(jnp.float32)[None, None]
    dec_bias = _position_bias(
        params["dec"]["rel_bias"], pos, pos, False, cfg
    ) + causal

    def run(enc_out, enc_mask, batch):
        cross_kv = _init_cross_kv(params, enc_out, cfg)
        mask_bias = _pad_bias(enc_mask)

        def step_fn(tok, step, caches):
            return decode_step(
                params, tok, step, caches, cross_kv, dec_bias, mask_bias,
                cfg, T,
            )

        return step_fn, _init_self_caches(cfg, batch, T)

    if num_beams <= 1:
        step_fn, caches = run(enc_out, src_mask, B)
        return greedy_scan(
            step_fn, caches, B, T,
            start_id=cfg.decoder_start_id, eos_id=cfg.eos_id,
            pad_id=cfg.pad_id, min_length=min_length,
        )
    K = num_beams
    step_fn, caches = run(
        jnp.repeat(enc_out, K, axis=0), jnp.repeat(src_mask, K, axis=0),
        B * K,
    )
    return beam_scan(
        step_fn, caches, B, cfg.vocab_size, T,
        num_beams=K, length_penalty=length_penalty,
        early_stopping=early_stopping, min_length=min_length,
        start_id=cfg.decoder_start_id, eos_id=cfg.eos_id,
        pad_id=cfg.pad_id,
    )


# ---- weight import ----


def _w(sd, key: str) -> np.ndarray:
    """HF Linear weight [out, in] → ours [in, out]."""
    return np.ascontiguousarray(sd[key].T)


def _attn_from(sd, prefix: str) -> Params:
    return {
        "q": _w(sd, f"{prefix}.q.weight"),
        "k": _w(sd, f"{prefix}.k.weight"),
        "v": _w(sd, f"{prefix}.v.weight"),
        "o": _w(sd, f"{prefix}.o.weight"),
    }


def _ffn_from(sd, prefix: str, gated: bool) -> Params:
    if gated:
        return {
            "wi_0": _w(sd, f"{prefix}.wi_0.weight"),
            "wi_1": _w(sd, f"{prefix}.wi_1.weight"),
            "wo": _w(sd, f"{prefix}.wo.weight"),
        }
    return {
        "wi": _w(sd, f"{prefix}.wi.weight"),
        "wo": _w(sd, f"{prefix}.wo.weight"),
    }


def from_state_dict(sd: Dict[str, np.ndarray], cfg: T5Config) -> Params:
    """HF T5 state dict → our param pytree (``T5Model`` /
    ``T5ForConditionalGeneration`` naming)."""
    sd = {k: np.asarray(v) for k, v in sd.items()}

    def branch(name: str, n_layers: int, cross: bool) -> Params:
        out: Params = {
            "rel_bias": sd[
                f"{name}.block.0.layer.0.SelfAttention"
                ".relative_attention_bias.weight"
            ],
            "layers": [],
            "ln_f": sd[f"{name}.final_layer_norm.weight"],
        }
        ff_idx = 2 if cross else 1
        for i in range(n_layers):
            p = f"{name}.block.{i}"
            blk: Params = {
                "attn": _attn_from(sd, f"{p}.layer.0.SelfAttention"),
                "ln1": sd[f"{p}.layer.0.layer_norm.weight"],
                "ffn": _ffn_from(
                    sd, f"{p}.layer.{ff_idx}.DenseReluDense", cfg.gated_ffn
                ),
                "ln2": sd[f"{p}.layer.{ff_idx}.layer_norm.weight"],
            }
            if cross:
                blk["cross"] = _attn_from(sd, f"{p}.layer.1.EncDecAttention")
                blk["ln_x"] = sd[f"{p}.layer.1.layer_norm.weight"]
            out["layers"].append(blk)
        return out

    params: Params = {
        "embed": sd["shared.weight"],
        "enc": branch("encoder", cfg.n_enc_layers, cross=False),
        "dec": branch("decoder", cfg.n_dec_layers, cross=True),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _w(sd, "lm_head.weight")
    return params


def is_hf_t5_dir(path: str) -> bool:
    cfg_path = os.path.join(path, "config.json")
    if not os.path.isdir(path) or not os.path.exists(cfg_path):
        return False
    try:
        with open(cfg_path) as f:
            return json.load(f).get("model_type") == "t5"
    except Exception:  # noqa: BLE001 — unreadable json resolves at load time
        return True  # claim it; load_hf_dir surfaces the real error


def load_hf_dir(path: str, **config_overrides) -> Tuple[T5Config, Params]:
    """Load (config, params) from a local HF T5 checkpoint directory."""
    cfg = T5Config.from_hf_json(
        os.path.join(path, "config.json"), **config_overrides
    )
    st_path = os.path.join(path, "model.safetensors")
    bin_path = os.path.join(path, "pytorch_model.bin")
    if os.path.exists(st_path):
        try:
            from safetensors.numpy import load_file

            return cfg, from_state_dict(load_file(st_path), cfg)
        except ImportError:
            pass
    if not os.path.exists(bin_path):
        raise FileNotFoundError(
            f"no model.safetensors or pytorch_model.bin under {path}"
        )
    import torch

    raw = torch.load(bin_path, map_location="cpu", weights_only=True)
    return cfg, from_state_dict({k: v.numpy() for k, v in raw.items()}, cfg)


# ---- tokenizer (gated on sentencepiece) ----

# Same bounded mtime-keyed cache discipline as the BPE loader (models/bpe.py):
# a pipelined drain calls the tokenizer per shard in both stage and finalize,
# and re-parsing an ~800 KB spiece.model on the host hot path is pure waste.
_SPM_CACHE_MAX = 8
_spm_cache: Dict[tuple, object] = {}
_spm_order: List[tuple] = []
_spm_lock = threading.Lock()


def hf_spm(path: str):
    """The checkpoint's SentencePiece tokenizer (``spiece.model``), cached
    per (directory, mtime). Needs the ``sentencepiece`` package — a clear,
    actionable error when absent (this environment does not bundle it)."""
    try:
        import sentencepiece as spm
    except ImportError as exc:
        raise RuntimeError(
            "serving a T5 checkpoint's text requires the sentencepiece "
            "package (pip install sentencepiece); the ids-level model path "
            "works without it"
        ) from exc
    model_path = os.path.join(path, "spiece.model")
    if not os.path.exists(model_path):
        raise ValueError(f"T5 checkpoint {path} has no spiece.model")
    key = (os.path.abspath(path), os.path.getmtime(model_path))
    with _spm_lock:
        hit = _spm_cache.get(key)
        if hit is not None:
            return hit
    sp = spm.SentencePieceProcessor()
    sp.Load(model_path)
    with _spm_lock:
        _spm_cache[key] = sp
        _spm_order.append(key)
        while len(_spm_order) > _SPM_CACHE_MAX:
            _spm_cache.pop(_spm_order.pop(0), None)
    return sp


def encode_pad_batch(
    sp, texts, cfg: T5Config, batch_buckets, length_buckets
) -> Tuple[np.ndarray, np.ndarray]:
    """``pieces </s>`` per row (the HF T5 tokenizer's convention) →
    (ids [B, L] int32, lengths [B] int32) with bucketed static shapes;
    bucket truncation keeps the trailing ``</s>`` (same semantics as
    ``models.bart.encode_pad_batch``)."""
    from agent_tpu.models.tokenizer import bucket_length

    max_len = cfg.max_src_len
    rows: List[List[int]] = [
        sp.EncodeAsIds(t)[: max_len - 1] + [cfg.eos_id] for t in texts
    ]
    L = bucket_length(min(max(len(r) for r in rows), max_len), length_buckets)
    B = bucket_length(len(rows), batch_buckets)
    ids = np.full((B, L), cfg.pad_id, dtype=np.int32)
    lengths = np.zeros(B, dtype=np.int32)
    for r, row in enumerate(rows):
        if len(row) > L:
            row = row[: L - 1] + [cfg.eos_id]
        ids[r, : len(row)] = row
        lengths[r] = len(row)
    return ids, lengths
