"""Tokenizers and batch padding.

The reference's ``map_tokenize`` is not a real tokenizer — it chunks characters
into fixed windows (reference ``ops/map_tokenize.py:6-9,24``); real tokenization
happened only inside torch/transformers for summarize (reference
``ops/map_summarize.py:49``). BASELINE.json upgrades the tokenize slot to a real
tokenizer. Constraints here: zero egress (no HF hub), deterministic, fast on
host, and producing **static shapes** for pjit (padding buckets, so ragged text
doesn't retrace the compiled op — SURVEY.md §7 "hard parts").

Two tokenizers:

- :class:`ByteTokenizer` — vocab-free byte-level tokenizer (256 byte ids +
  specials). Reversible, language-agnostic, no artifacts. Default everywhere.
- :class:`WordPieceTokenizer` — greedy longest-prefix wordpiece over a loadable
  vocab (one token per line, ``##`` continuation), with a corpus-trainer for
  tests and local vocab building. API-compatible with BERT-style vocab files so
  real vocabs drop in when present on disk.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

# Special token ids are shared by both tokenizers so models don't care which
# produced their input.
PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
UNK_ID = 3
N_SPECIAL = 4

SPECIAL_TOKENS = ("<pad>", "<bos>", "<eos>", "<unk>")

# Default padding buckets: powers of two from 16 up. One compiled executable per
# bucket per batch size — the executable cache stays small and recompiles stop
# once the buckets are warm.
# Powers of two PLUS their midpoints: a pure pow2 ladder wastes up to 2×
# padding at the bucket edge (measured in the 10M-row drain: ~70-byte rows
# bucketing to 128 ran summarize at 5.2k rows/s where the 64 bucket ran
# 8.2k — ~44% of every matmul was padding). A ratio-1.5 ladder caps the
# worst-case pad multiplier at ~1.5× (a 65-token row pads to 96 = 1.48×)
# vs the pow2 ladder's 2×; all entries stay multiples of 8 (TPU sublane)
# and the ≥2048 ones multiples of 512 (the flash kernel's tile
# divisibility gate).
DEFAULT_BUCKETS = (
    16, 32, 48, 64, 96, 128, 192, 256, 384, 512, 768, 1024, 1536, 2048,
    3072, 4096,
)


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: id = byte + N_SPECIAL. Vocab size 260."""

    vocab_size = 256 + N_SPECIAL
    pad_id, bos_id, eos_id, unk_id = PAD_ID, BOS_ID, EOS_ID, UNK_ID

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        ids = [b + N_SPECIAL for b in text.encode("utf-8")]
        if add_bos:
            ids.insert(0, BOS_ID)
        if add_eos:
            ids.append(EOS_ID)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        raw = bytes(i - N_SPECIAL for i in ids if i >= N_SPECIAL)
        return raw.decode("utf-8", errors="replace")


_WORD_RE = re.compile(r"\w+|[^\w\s]", re.UNICODE)


@dataclass
class WordPieceTokenizer:
    """Greedy longest-match wordpiece (BERT-style ``##`` continuations)."""

    vocab: Dict[str, int] = field(default_factory=dict)
    lowercase: bool = True
    max_word_chars: int = 64

    pad_id, bos_id, eos_id, unk_id = PAD_ID, BOS_ID, EOS_ID, UNK_ID

    def __post_init__(self) -> None:
        if not self.vocab:
            self.vocab = {t: i for i, t in enumerate(SPECIAL_TOKENS)}

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @classmethod
    def from_file(cls, path: str, lowercase: bool = True) -> "WordPieceTokenizer":
        """Load a BERT-style vocab file: one token per line, id = line number."""
        vocab: Dict[str, int] = {}
        with open(path, "r", encoding="utf-8") as f:
            for i, line in enumerate(f):
                vocab[line.rstrip("\n")] = i
        return cls(vocab=vocab, lowercase=lowercase)

    def save(self, path: str) -> None:
        inv = sorted(self.vocab.items(), key=lambda kv: kv[1])
        with open(path, "w", encoding="utf-8") as f:
            for tok, _ in inv:
                f.write(tok + "\n")

    @classmethod
    def train(
        cls,
        corpus: Iterable[str],
        vocab_size: int = 8192,
        lowercase: bool = True,
    ) -> "WordPieceTokenizer":
        """Frequency-based wordpiece trainer: whole words by count, then all
        single characters (with ``##`` variants) as the fallback alphabet.

        Not BPE-merge-optimal — it is a deterministic, dependency-free trainer
        good enough to build local vocabs for tests and demos.
        """
        counts: Dict[str, int] = {}
        chars: Dict[str, int] = {}
        for text in corpus:
            if lowercase:
                text = text.lower()
            for w in _WORD_RE.findall(text):
                counts[w] = counts.get(w, 0) + 1
                # Register both positional variants of every character so any
                # word over the seen alphabet is always encodable piece-wise.
                for c in w:
                    chars[c] = chars.get(c, 0) + 1
                    chars["##" + c] = chars.get("##" + c, 0) + 1
        vocab: Dict[str, int] = {t: i for i, t in enumerate(SPECIAL_TOKENS)}
        # Alphabet first so every word is always encodable.
        for piece in sorted(chars, key=lambda p: (-chars[p], p)):
            if len(vocab) >= vocab_size:
                break
            vocab.setdefault(piece, len(vocab))
        for w in sorted(counts, key=lambda w: (-counts[w], w)):
            if len(vocab) >= vocab_size:
                break
            vocab.setdefault(w, len(vocab))
        return cls(vocab=vocab, lowercase=lowercase)

    def _encode_word(self, word: str) -> List[int]:
        if len(word) > self.max_word_chars:
            return [self.unk_id]
        ids: List[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while end > start:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                pid = self.vocab.get(piece)
                if pid is not None:
                    piece_id = pid
                    break
                end -= 1
            if piece_id is None:
                return [self.unk_id]
            ids.append(piece_id)
            start = end
        return ids

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        if self.lowercase:
            text = text.lower()
        ids: List[int] = []
        if add_bos:
            ids.append(self.bos_id)
        for w in _WORD_RE.findall(text):
            ids.extend(self._encode_word(w))
        if add_eos:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        inv = {i: t for t, i in self.vocab.items()}
        out: List[str] = []
        for i in ids:
            tok = inv.get(int(i))
            if tok is None or tok in SPECIAL_TOKENS:
                continue
            if tok.startswith("##") and out:
                out[-1] += tok[2:]
            else:
                out.append(tok)
        return " ".join(out)


def byte_encode_pad(
    texts: Sequence[str],
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    batch_buckets: Optional[Sequence[int]] = None,
    max_len_cap: Optional[int] = None,
    add_bos: bool = False,
    add_eos: bool = False,
    raw_uint8: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Fused byte-tokenize + pad: texts → (ids[B, L] int32, lengths[B] int32).

    The hot-path replacement for ``ByteTokenizer.encode`` + ``pad_batch`` on
    large batches: each row is one ``np.frombuffer`` over the UTF-8 bytes
    (C speed) instead of a per-byte Python list — same ids (byte + N_SPECIAL),
    same bucketed static shapes, same truncation semantics (BOS/EOS count
    toward the cap, exactly like ``encode(add_bos, add_eos)[:cap]``). Returns
    per-row lengths (not a mask): the device path rebuilds the mask on-chip.

    ``raw_uint8=True`` returns the UNSHIFTED bytes as uint8 — the minimal
    wire format for tunnel-limited host→device links (1 byte/token instead
    of 2): the compiled program reconstructs ``ids = (raw + N_SPECIAL) *
    mask`` on device (see ``map_classify_tpu``), which is exact because with
    no BOS/EOS every non-pad id is ``byte + N_SPECIAL`` and the mask already
    distinguishes a body NUL byte (raw 0, masked in) from padding (raw 0,
    masked out). Incompatible with ``add_bos``/``add_eos``.
    """
    if raw_uint8 and (add_bos or add_eos):
        raise ValueError("raw_uint8 wire cannot carry BOS/EOS specials")
    cap = max_len_cap if max_len_cap is not None else buckets[-1]
    off = int(add_bos)
    bufs = [t.encode("utf-8") for t in texts]
    rows = len(bufs)
    lens = np.fromiter((len(b) for b in bufs), dtype=np.int64, count=rows)
    # Exactly encode(add_bos, add_eos)[:cap] then pad_batch: BOS/EOS join the
    # stream before truncation (a too-long text loses its EOS), and rows
    # truncate again to the top bucket when cap exceeds it (bucket_length's
    # "callers truncate to it" contract).
    totals = np.minimum(off + lens + int(add_eos), cap)
    L = bucket_length(max(1, int(totals.max()) if rows else 1), buckets)
    totals = np.minimum(totals, L)
    B = bucket_length(max(1, rows), batch_buckets) if batch_buckets else rows
    ids = np.zeros((B, L), dtype=np.uint8 if raw_uint8 else np.int32)
    lengths = np.zeros(B, dtype=np.int32)
    lengths[:rows] = totals
    nb = np.zeros(B, dtype=np.int64)
    nb[:rows] = np.maximum(totals - off, 0)
    nb[:rows] = np.minimum(nb[:rows], lens)
    if rows:
        # One vectorized scatter instead of a per-row copy loop: all texts
        # join into one flat byte view, and each row r pulls its
        # flat[start_r : start_r + nb_r] slice via a masked gather — ~3
        # array passes over [B, L] (a few ms at 8k×128) vs 8k Python
        # iterations.
        flat = np.frombuffer(b"".join(bufs), dtype=np.uint8)
        starts = np.zeros(rows, dtype=np.int64)
        if rows > 1:
            np.cumsum(lens[:-1], out=starts[1:])
        cols = np.arange(L, dtype=np.int64)[None, :]
        body = (cols >= off) & (cols < off + nb[:rows, None])
        src = starts[:, None] + (cols - off)
        if flat.size:
            ids[:rows][body] = flat[np.clip(src, 0, flat.size - 1)][body]
    if raw_uint8:
        return ids, lengths
    cols = np.arange(L)[None, :]
    body = (cols >= off) & (cols < off + nb[:, None])
    ids[body] += N_SPECIAL                     # every body byte, NULs included
    if add_bos and rows:
        ids[:rows, 0][totals > 0] = BOS_ID
    if add_eos and rows:
        fits = np.flatnonzero(off + lens + 1 <= np.minimum(cap, L))
        ids[fits, (off + nb[fits]).astype(np.int64)] = EOS_ID
    return ids, lengths


def bucket_length(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket ≥ n (or the largest bucket — callers truncate to it)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def pad_batch(
    seqs: Sequence[Sequence[int]],
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    pad_id: int = PAD_ID,
    batch_buckets: Optional[Sequence[int]] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Ragged int lists → (ids[B, L], mask[B, L]) with bucketed static shapes.

    L is the smallest length bucket covering the longest sequence (longer
    sequences are truncated to the top bucket). If ``batch_buckets`` is given,
    B is also bucketed, with all-pad rows appended — both dims then come from
    small fixed sets, so the jit executable cache stays warm (SURVEY.md §7).
    """
    max_len = max((len(s) for s in seqs), default=1)
    L = bucket_length(max(1, max_len), buckets)
    rows = len(seqs)
    B = bucket_length(max(1, rows), batch_buckets) if batch_buckets else rows
    ids = np.full((B, L), pad_id, dtype=np.int32)
    mask = np.zeros((B, L), dtype=np.int32)
    for r, s in enumerate(seqs):
        s = list(s)[:L]
        ids[r, : len(s)] = s
        mask[r, : len(s)] = 1
    return ids, mask


def get_tokenizer(kind: str = "byte", vocab_path: Optional[str] = None):
    """Factory used by ops: ``byte`` (default), ``wordpiece`` (needs a
    vocab.txt path), or ``bpe`` (GPT-2/BART byte-level BPE; needs a
    directory holding vocab.json + merges.txt, e.g. an HF checkpoint dir)."""
    if kind == "byte":
        return ByteTokenizer()
    if kind == "wordpiece":
        if vocab_path:
            return WordPieceTokenizer.from_file(vocab_path)
        raise ValueError("wordpiece tokenizer requires vocab_path")
    if kind == "bpe":
        if vocab_path:
            from agent_tpu.models.bpe import ByteLevelBPE

            return ByteLevelBPE.from_dir(vocab_path)
        raise ValueError(
            "bpe tokenizer requires vocab_path (dir with vocab.json + merges.txt)"
        )
    raise ValueError(f"unknown tokenizer kind {kind!r}")
