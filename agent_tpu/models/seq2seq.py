"""Encoder-decoder (T5-class) seq2seq with scan-based decode — behind
``map_summarize``.

The reference summarized with torch BART ``model.generate(num_beams=4)`` on the
host CPU (reference ``ops/map_summarize.py:52-59``, ``SUMMARIZE_FORCE_CPU``
default on, ``:10``) — the "zero CPU-side model execution" target of
BASELINE.json. Here generation is a single jit-compiled program: the encoder
runs once, then ``lax.scan`` steps the decoder over a **static** number of
positions with a preallocated KV cache updated via ``dynamic_update_slice`` —
no per-step retrace, no host round-trips inside the decode loop (SURVEY.md §7
"hard parts": autoregressive decode under pjit).

Greedy decode is the default; beam search stays optional per VERDICT item 7.
Weights are deterministic from the model id or loaded from ``.npz``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agent_tpu.models import layers
from agent_tpu.models.layers import Params
from agent_tpu.models.tokenizer import BOS_ID, EOS_ID, PAD_ID


@dataclass(frozen=True)
class Seq2SeqConfig:
    vocab_size: int = 260
    d_model: int = 256
    n_heads: int = 8
    n_enc_layers: int = 4
    n_dec_layers: int = 4
    d_ff: int = 1024
    max_src_len: int = 1024       # reference truncates input at 1024 (:49)
    max_tgt_len: int = 130        # reference generate max_length default (:46)
    dtype: str = "bfloat16"
    # "int8": W8A8 quantized matmuls (models.quant) in encode AND decode —
    # the reference's INT8 device execution, TPU-native. "w8a16": weight-only
    # int8 (activations stay at dtype) — the decode-mode recipe for
    # HBM-bound thin matmuls.
    quant: str = "none"

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def init_params(cfg: Seq2SeqConfig, model_id: str = "summarize-default") -> Params:
    key = layers.seed_from(model_id)
    n = cfg.n_enc_layers + cfg.n_dec_layers
    ks = jax.random.split(key, n + 3)
    max_len = max(cfg.max_src_len, cfg.max_tgt_len)
    return {
        "embed": jax.random.normal(
            ks[0], (cfg.vocab_size, cfg.d_model), dtype=jnp.float32
        ) * 0.02,
        "pos": jnp.asarray(layers.sinusoidal_positions(max_len, cfg.d_model)),
        "enc": [
            layers.init_block(ks[1 + i], cfg.d_model, cfg.n_heads, cfg.d_ff)
            for i in range(cfg.n_enc_layers)
        ],
        "dec": [
            layers.init_block(
                ks[1 + cfg.n_enc_layers + i], cfg.d_model, cfg.n_heads, cfg.d_ff,
                cross=True,
            )
            for i in range(cfg.n_dec_layers)
        ],
        "ln_enc": layers.init_layer_norm(cfg.d_model),
        "ln_dec": layers.init_layer_norm(cfg.d_model),
        # Output projection ties to the embedding (transposed) — standard and
        # halves the param count; no separate head matrix.
    }


def encode(params: Params, src_ids: jax.Array, src_mask: jax.Array,
           cfg: Seq2SeqConfig,
           attn_fn=layers.dot_product_attention) -> jax.Array:
    dtype = cfg.compute_dtype
    L = src_ids.shape[1]
    x = params["embed"].astype(dtype)[src_ids] + params["pos"][:L].astype(dtype)[None]
    attn_mask = layers.pad_mask_to_attn(src_mask)
    for block in params["enc"]:
        x = layers.encoder_block(block, x, attn_mask, dtype, attn_fn=attn_fn)
    return layers.layer_norm(params["ln_enc"], x)


def _empty_cache(cfg: Seq2SeqConfig, batch: int) -> list:
    d_head = cfg.d_model // cfg.n_heads
    shape = (batch, cfg.n_heads, cfg.max_tgt_len, d_head)
    return [
        {
            "k": jnp.zeros(shape, dtype=cfg.compute_dtype),
            "v": jnp.zeros(shape, dtype=cfg.compute_dtype),
        }
        for _ in range(cfg.n_dec_layers)
    ]


def _decode_step(
    params: Params,
    tok: jax.Array,           # [B] current input token
    step: jax.Array,          # scalar int32 position, or [B] per-row positions
    enc_out: jax.Array,       # [B, Ls, d]
    enc_mask: jax.Array,      # [B, Ls]
    caches: list,
    cfg: Seq2SeqConfig,
) -> Tuple[jax.Array, list]:
    """One decoder step over the KV cache; returns (logits [B, V], caches).

    ``step`` may be a **[B] vector** of per-row positions — the continuous-
    batching case (ISSUE 15), where each running-batch slot sits at its own
    decode depth. The per-row math (position embedding gather, per-row
    causal mask, per-row cache scatter) computes exactly the values the
    scalar path computes for a batch whose rows all share one position, so
    a slot's step stream is bit-identical to a solo scalar-step decode.

    ``caches`` may be the **paged** pytree ``{"table": [B, MAXB] int32,
    "layers": [{"k","v"}: [NB, H, BS, D]]}`` (``make_paged_cache_factory``,
    ISSUE 16): layer caches become shared block pools indexed through the
    per-row block table, detected structurally so the step signature — and
    every caller — is unchanged. Paged decode requires the vector-``step``
    form; the mask math is identical, and the attention layer slices its
    paged view back to ``max_tgt_len`` so the emitted logits stay
    bit-identical to a dense-cache decode.
    """
    dtype = cfg.compute_dtype
    paged = isinstance(caches, dict) and "table" in caches
    if paged and getattr(step, "ndim", 0) != 1:
        raise ValueError(
            "paged KV caches require per-row vector positions (the "
            "continuous-batching step); scan decode uses dense caches"
        )
    table = caches["table"] if paged else None
    layer_caches = caches["layers"] if paged else caches
    x = params["embed"].astype(dtype)[tok][:, None, :]  # [B, 1, d]
    positions = jnp.arange(cfg.max_tgt_len)
    if getattr(step, "ndim", 0) == 1:
        x = x + params["pos"].astype(dtype)[step][:, None, :]
        # Per-row causal mask: row b attends to cache positions <= step[b].
        self_mask = (
            positions[None, :] <= step[:, None]
        ).astype(jnp.int32)[:, None, None, :]
    else:
        x = x + jax.lax.dynamic_slice_in_dim(
            params["pos"].astype(dtype), step, 1, axis=0
        )[None]
        # Self-attention mask: attend to cache positions <= step.
        self_mask = (positions <= step).astype(jnp.int32)[None, None, None, :]
    enc_attn_mask = enc_mask[:, None, None, :]
    new_layers = []
    for block, cache in zip(params["dec"], layer_caches):
        x, cache = layers.decoder_block(
            block, x, self_mask, enc_out, enc_attn_mask, dtype,
            cache=cache, cache_index=step, block_table=table,
        )
        new_layers.append(cache)
    x = layers.layer_norm(params["ln_dec"], x)[:, 0]  # [B, d]
    logits = jnp.dot(x.astype(dtype), params["embed"].astype(dtype).T)
    new_caches = {"table": table, "layers": new_layers} if paged else new_layers
    return logits.astype(jnp.float32), new_caches


def greedy_generate(
    params: Params,
    src_ids: jax.Array,    # [B, Ls] int32
    src_mask: jax.Array,   # [B, Ls] int32
    cfg: Seq2SeqConfig,
    max_new_tokens: int,
    min_length: int = 0,
    attn_fn=layers.dot_product_attention,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy decode under one jit trace: ``lax.scan`` over static steps.

    Returns (tokens [B, max_new_tokens], lengths [B]) — generation stops
    contributing after EOS per row (tokens after EOS are PAD), but the scan
    always runs the static step count so the executable is shape-stable.

    ``attn_fn`` applies to the *encoder* (where the long context lives — the
    ring/sp path, SURVEY.md §5.7); decode steps query one position against the
    KV cache, where sequence sharding buys nothing.
    """
    from agent_tpu.models.decoding import greedy_scan

    B = src_ids.shape[0]
    enc_out = encode(params, src_ids, src_mask, cfg, attn_fn=attn_fn)

    def step_fn(tok, step, caches):
        return _decode_step(params, tok, step, enc_out, src_mask, caches, cfg)

    return greedy_scan(
        step_fn, _empty_cache(cfg, B), B, max_new_tokens,
        start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID,
        min_length=min_length,
    )


def greedy_generate_from_encoded(
    params: Params,
    enc_out: jax.Array,    # [B, Ls, d] encoder output (cfg.compute_dtype)
    src_mask: jax.Array,   # [B, Ls] int32
    cfg: Seq2SeqConfig,
    max_new_tokens: int,
    min_length: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy decode from a PRE-COMPUTED encoder output — the decoder half
    of the MPMD pipeline split (ISSUE 7 stretch, arXiv 2412.14374): an
    encode-stage agent ships ``enc_out`` through the controller and a
    decode-stage agent resumes here. Same scan/caches/EOS semantics as
    :func:`greedy_generate`, which is exactly ``encode(...)`` composed with
    this function."""
    from agent_tpu.models.decoding import greedy_scan

    B = enc_out.shape[0]
    enc_out = enc_out.astype(cfg.compute_dtype)

    def step_fn(tok, step, caches):
        return _decode_step(params, tok, step, enc_out, src_mask, caches, cfg)

    return greedy_scan(
        step_fn, _empty_cache(cfg, B), B, max_new_tokens,
        start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID,
        min_length=min_length,
    )


def beam_generate(
    params: Params,
    src_ids: jax.Array,    # [B, Ls] int32
    src_mask: jax.Array,   # [B, Ls] int32
    cfg: Seq2SeqConfig,
    max_new_tokens: int,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    early_stopping: bool = False,
    min_length: int = 0,
    attn_fn=layers.dot_product_attention,
) -> Tuple[jax.Array, jax.Array]:
    """Beam-search decode under one jit trace — static shapes throughout.

    The reference decoded with torch ``generate(num_beams=4)`` on the host
    CPU (reference ``ops/map_summarize.py:52-59``). Here beams flatten into
    the batch dim (``B*K`` rows share the decode-step executable with greedy),
    every step does one top-K over ``[B, K*V]`` joint scores, and beam
    reordering gathers the KV caches along the beam axis — all inside
    ``lax.scan``, so the program never retraces per step.

    Semantics are HF ``BeamSearchScorer``-exact (see ``decoding.beam_scan``):
    EOS hypotheses bank into a K-slot finished store normalized by
    ``generated_length ** length_penalty``; ``early_stopping=True`` closes a
    row as soon as the store fills (HF's generic default is False;
    bart-large-cnn — the reference's model — generated with True).

    Returns (tokens [B, max_new_tokens], lengths [B]) like
    :func:`greedy_generate` (``num_beams=1`` reduces to exactly greedy).
    """
    from agent_tpu.models.decoding import beam_scan

    B, K = src_ids.shape[0], num_beams
    enc_out = encode(params, src_ids, src_mask, cfg, attn_fn=attn_fn)
    enc_out = jnp.repeat(enc_out, K, axis=0)            # [B*K, Ls, d]
    enc_mask = jnp.repeat(src_mask, K, axis=0)          # [B*K, Ls]

    def step_fn(tok, step, caches):
        return _decode_step(params, tok, step, enc_out, enc_mask, caches, cfg)

    return beam_scan(
        step_fn, _empty_cache(cfg, B * K), B, cfg.vocab_size, max_new_tokens,
        num_beams=K, start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID,
        length_penalty=length_penalty, early_stopping=early_stopping,
        min_length=min_length,
    )


def make_positional_step(params: Params, cfg: Seq2SeqConfig):
    """The per-row-position decode step the continuous-batching engine
    (``models.decoding.ContinuousBatcher``) drives: unlike the scan engines'
    closures, the encoder state is an ARGUMENT, because slots join a running
    batch with their own encoder output (the prefill/decode split — prefill
    produced ``enc_out`` earlier, possibly on another agent, cf.
    ``greedy_generate_from_encoded``)."""

    def step_fn(tok, pos_rows, caches, enc_out, enc_mask):
        return _decode_step(
            params, tok, pos_rows, enc_out.astype(cfg.compute_dtype),
            enc_mask, caches, cfg,
        )

    return step_fn


def make_cache_factory(cfg: Seq2SeqConfig):
    """``rows -> empty KV caches`` for the continuous engine's slot store."""

    def factory(rows: int) -> list:
        return _empty_cache(cfg, rows)

    return factory


def make_paged_cache_factory(
    cfg: Seq2SeqConfig, block_size: int = 16, pool_blocks: int = 0
):
    """``rows -> paged KV caches`` for the continuous engine (ISSUE 16).

    Instead of ``rows × max_tgt_len`` dense reservation, each decoder layer
    holds ONE shared pool of ``pool_blocks`` fixed-size KV blocks
    ``[NB, H, block_size, d_head]`` plus a per-row block table
    ``[rows, ceil(max_tgt_len / block_size)]`` mapping logical block →
    pool block. Pool block 0 is reserved as the trash block (the engine
    points unallocated/released entries there), so ``pool_blocks`` counts
    one unusable block. ``pool_blocks=0`` auto-sizes to dense parity
    (``rows * MAXB + 1``) — same worst-case HBM, no admission stalls; shrink
    it to trade admission headroom for resident-memory savings, since live
    requests only hold ``ceil(limit / block_size)`` blocks per row.
    """
    bs = int(block_size)
    if bs < 1:
        raise ValueError("block_size must be >= 1")
    maxb = -(-cfg.max_tgt_len // bs)
    d_head = cfg.d_model // cfg.n_heads

    def factory(rows: int) -> dict:
        nb = int(pool_blocks) or rows * maxb + 1
        if nb < maxb + 1:
            raise ValueError(
                f"pool_blocks={nb} cannot seat one max-length row "
                f"({maxb} blocks + trash)"
            )
        return {
            "table": jnp.zeros((rows, maxb), dtype=jnp.int32),
            "layers": [
                {
                    "k": jnp.zeros(
                        (nb, cfg.n_heads, bs, d_head),
                        dtype=cfg.compute_dtype,
                    ),
                    "v": jnp.zeros(
                        (nb, cfg.n_heads, bs, d_head),
                        dtype=cfg.compute_dtype,
                    ),
                }
                for _ in range(cfg.n_dec_layers)
            ],
        }

    return factory


def load_npz(path: str, cfg: Seq2SeqConfig) -> Params:
    """Load params from a flat ``.npz`` (keys like ``dec.0.xattn.wq``)."""
    return layers.assign_from_npz(init_params(cfg, model_id=path), path)
