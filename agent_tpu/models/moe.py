"""Mixture-of-Experts FFN with expert parallelism over an ``ep`` mesh axis.

The reference had no MoE (SURVEY.md §2.8: "No (no MoE models)"; the mesh
design brief was "must not preclude it"). This module goes one step further
and implements it, Mesh-TensorFlow/Switch style, in the einsum-dispatch
formulation that XLA shards well:

- Router: top-1 gating over ``n_experts`` with a capacity limit per expert
  (tokens over capacity are dropped — their residual path carries them, the
  standard Switch behavior).
- Dispatch/combine are one-hot einsums, so expert inputs materialize as an
  ``[E, C, d]`` tensor whose expert dim shards over ``ep`` — XLA inserts the
  all-to-all at the dispatch/combine boundaries when the mesh has an ``ep``
  axis (``moe_param_specs``/``expert_batch_spec``); on a 1-axis mesh the
  same program runs unsharded.
- Static shapes throughout: capacity is computed from a factor at init time,
  never from data.

``build_mesh`` already accepts arbitrary extra axes (``MESH_SHAPE=
"dp=2,ep=4"``), so this slots into the existing runtime unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from agent_tpu.models import layers
from agent_tpu.models.layers import Params


@dataclass(frozen=True)
class MoeConfig:
    d_model: int = 128
    d_ff: int = 512
    n_experts: int = 4
    capacity_factor: float = 1.25
    dtype: str = "float32"

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    def capacity(self, n_tokens: int) -> int:
        """Static per-expert token capacity for a given (padded) token count."""
        return max(1, int(np.ceil(n_tokens / self.n_experts * self.capacity_factor)))


def init_moe_ffn(key: jax.Array, cfg: MoeConfig) -> Params:
    """Router + expert-stacked FFN weights (expert dim first → ep-shardable)."""
    kr, k1, k2 = jax.random.split(key, 3)
    scale_in = 1.0 / np.sqrt(cfg.d_model)
    scale_out = 1.0 / np.sqrt(cfg.d_ff)
    return {
        "router": {
            "w": jax.random.normal(kr, (cfg.d_model, cfg.n_experts), jnp.float32)
            * scale_in,
        },
        "wi": jax.random.normal(
            k1, (cfg.n_experts, cfg.d_model, cfg.d_ff), jnp.float32
        ) * scale_in,
        "wo": jax.random.normal(
            k2, (cfg.n_experts, cfg.d_ff, cfg.d_model), jnp.float32
        ) * scale_out,
    }


def moe_param_specs(cfg: MoeConfig = None) -> Params:
    """PartitionSpecs: experts over ``ep``, router replicated. The layout
    is structural (no config dependence); ``cfg`` stays for call-site
    symmetry with the other spec builders."""
    return {
        "router": {"w": P()},
        "wi": P("ep", None, None),
        "wo": P("ep", None, None),
    }


def expert_batch_spec() -> P:
    """[G, E, C, d] expert-batch tensors: expert dim over ``ep``."""
    return P(None, "ep", None, None)


# Routing group size (tokens). Capacity — and therefore the [t, E, C]
# dispatch/combine tensors and their einsums — scales with the token count
# being routed TOGETHER, so routing a whole serving batch as one group makes
# the dispatch einsums dominate: at BERT-base-8E serving shapes (B 1024 ×
# L 512 = 524k tokens) the one-group formulation measured **51 rows/s** vs
# the dense-FFN model's 1,097. Bounded groups are the standard GShard/Switch
# answer — dispatch/FFN flops ≈ G·cf / (4·d_ff). Measured on v5e (bench
# ``moe`` leg, same shapes): G=4096 → 473 rows/s, 1024 → 595, 512 → 635,
# 256 → 615, 128 → 669. Default 512 = one seq-512 row per group (capacity
# 80 at E=8/cf 1.25 — small-group drop variance still bounded) from the
# plateau. Tokens route independently per group; drops depend only on
# in-group competition.
MOE_GROUP_TOKENS = 512


def moe_ffn(params: Params, x: jax.Array, cfg: MoeConfig,
            mesh=None, group_size: int = 0) -> tuple:
    """Switch FFN. ``x``: [T, d_model] tokens → ([T, d_model], aux_loss).

    Returns the combined expert outputs (zero rows for capacity-dropped
    tokens — callers add the residual) and the load-balancing auxiliary loss
    (mean fraction·probability product, Switch §2.2 shape).

    Tokens are routed in fixed groups of ``group_size`` (default
    ``MOE_GROUP_TOKENS``; a T below that is one group, so small inputs keep
    the exact ungrouped semantics) with per-group expert capacity
    ``cfg.capacity(group)`` — see the ``MOE_GROUP_TOKENS`` note for why
    unbounded groups are quadratically wrong. ``T`` is zero-padded up to a
    group multiple; pad tokens route like real ones (they can occupy
    capacity in the final, partial group only) and their outputs are
    discarded.

    With ``mesh`` given, the [G, E, C, d] expert batches carry an explicit
    ``expert_batch_spec`` sharding constraint so the expert dim provably
    lands on ``ep`` (not left to XLA propagation from the param specs).
    """
    dtype = cfg.compute_dtype
    T, d = x.shape
    E = cfg.n_experts
    if T == 0:  # empty token set: nothing to route, aux is defined as 0
        return x, jnp.float32(0.0)
    group = min(T, group_size or MOE_GROUP_TOKENS)
    pad = (-T) % group
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, d), x.dtype)], axis=0)
    n_g = (T + pad) // group
    C = cfg.capacity(group)
    xg = x.reshape(n_g, group, d)

    logits = jnp.einsum(
        "gtd,de->gte", xg.astype(jnp.float32), params["router"]["w"]
    )                                                                # [g, t, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                          # [g, t]
    gate = jnp.take_along_axis(probs, expert_idx[..., None], axis=2)[..., 0]

    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)        # [g, t, E]
    # Position of each token within its expert's in-group queue (0-based);
    # zero at non-routed experts, so summing over E extracts the position.
    pos = jnp.cumsum(onehot, axis=1) * onehot - onehot               # [g, t, E]
    # one_hot emits an all-zero row for pos >= C — that IS the capacity drop.
    pos_oh = jax.nn.one_hot(
        pos.sum(axis=-1).astype(jnp.int32), C, dtype=jnp.float32
    )                                                                # [g, t, C]
    dispatch = onehot[..., None] * pos_oh[:, :, None, :]             # [g, t, E, C]
    combine = dispatch * gate[..., None, None]

    def constrain(t):
        if mesh is None:
            return t
        from jax.sharding import NamedSharding

        return jax.lax.with_sharding_constraint(
            t, NamedSharding(mesh, expert_batch_spec())
        )

    expert_in = constrain(jnp.einsum(
        "gtec,gtd->gecd", dispatch.astype(dtype), xg.astype(dtype)
    ))                                                               # [g, E, C, d]
    from agent_tpu.models import quant

    if quant.is_quantized(params["wi"]):
        # int8 expert FFN (quant.qmoe_expert): same W8A8 recipe as the dense
        # families, per-expert weight scales; router/dispatch/combine stay
        # high-precision.
        h = jax.nn.gelu(quant.qmoe_expert(params["wi"], expert_in, dtype))
        expert_out = constrain(quant.qmoe_expert(params["wo"], h, dtype))
    elif quant.is_weight_only(params["wi"]):
        # W8A16 expert FFN (quant.wmoe_expert): int8-resident expert tables,
        # activations stay in the compute dtype — the decode-mode recipe,
        # same per-expert scales and routing as the W8A8 path.
        h = jax.nn.gelu(quant.wmoe_expert(params["wi"], expert_in, dtype))
        expert_out = constrain(quant.wmoe_expert(params["wo"], h, dtype))
    else:
        h = jax.nn.gelu(jnp.einsum(
            "gecd,edf->gecf", expert_in, params["wi"].astype(dtype)
        ))
        expert_out = constrain(jnp.einsum(
            "gecf,efd->gecd", h, params["wo"].astype(dtype)
        ))
    y = jnp.einsum("gtec,gecd->gtd", combine.astype(dtype), expert_out)
    y = y.reshape(n_g * group, d)[:T]

    # Switch load-balance aux loss: E · Σ_e fraction_e · mean_prob_e, per
    # routing group, averaged over groups (equal group sizes ⇒ identical to
    # the global formula when n_g == 1). Pad tokens are EXCLUDED from the
    # statistics: they route like real tokens (tail capacity slots only)
    # but a zero row's uniform-softmax argmax is expert 0, and counting
    # them would bias the router gradient against it every step T is not
    # a group multiple.
    valid = (
        jnp.arange(n_g * group).reshape(n_g, group) < T
    )[..., None].astype(jnp.float32)                                 # [g, t, 1]
    vcount = jnp.maximum(valid.sum(axis=1), 1.0)                     # [g, 1]
    fraction = (onehot * valid).sum(axis=1) / vcount                 # [g, E]
    mean_prob = (probs * valid).sum(axis=1) / vcount
    aux = ((fraction * mean_prob).sum(axis=-1) * E).mean()
    return y.astype(x.dtype), aux


def moe_block(params: Params, x: jax.Array, cfg: MoeConfig) -> tuple:
    """Pre-LN residual MoE block over [B, L, d] activations → (y, aux)."""
    B, L, d = x.shape
    h = layers.layer_norm(params["ln"], x).reshape(B * L, d)
    y, aux = moe_ffn(params["moe"], h, cfg)
    return x + y.reshape(B, L, d), aux


def init_moe_block(key: jax.Array, cfg: MoeConfig) -> Params:
    return {
        "ln": layers.init_layer_norm(cfg.d_model),
        "moe": init_moe_ffn(key, cfg),
    }
