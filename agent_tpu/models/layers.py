"""Pure-JAX transformer building blocks shared by every model in the framework.

Design stance (SURVEY.md §7): models are *functions over param pytrees*, not
classes — the idiomatic JAX shape. Parameters are plain nested dicts of
``jnp.float32`` arrays; compute casts to the runtime's compute dtype (bf16 on
TPU — the MXU-native choice) and accumulates softmax/logits in f32.

Determinism: all init goes through :func:`seed_from` + ``jax.random.fold_in``,
so a model id string fully determines the weights (zero egress — no hub
downloads, reference ``ops/map_summarize.py:29-30`` pulled from HF instead).

Sharding: these functions are GSPMD-friendly — no data-dependent shapes, heads
and ffn hidden kept as separate, shardable axes. Explicit tp/sp placement is
applied by callers (op executors / the train step) via in_shardings and
``with_sharding_constraint``; the ring-attention sp path lives in
``agent_tpu.parallel.ring`` and slots in behind :func:`attention`'s interface.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]

NEG_INF = -1e9  # additive mask value; finite so bf16 stays NaN-free


def seed_from(name: str) -> jax.Array:
    """A PRNG key fully determined by ``name`` (model id → weights)."""
    h = hashlib.sha256(name.encode("utf-8")).digest()
    return jax.random.PRNGKey(int.from_bytes(h[:4], "big"))


def _dense_init(key: jax.Array, shape: Tuple[int, ...], fan_in: int) -> jax.Array:
    scale = 1.0 / np.sqrt(max(1, fan_in))
    return jax.random.normal(key, shape, dtype=jnp.float32) * scale


def init_dense(key: jax.Array, d_in: int, d_out: int) -> Params:
    return {
        "w": _dense_init(key, (d_in, d_out), d_in),
        "b": jnp.zeros((d_out,), dtype=jnp.float32),
    }


def dense(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    from agent_tpu.models import quant

    if quant.is_quantized(p):  # int8 leaf (models.quant leaf convention)
        return quant.qdense(p, x, dtype)
    if quant.is_weight_only(p):  # W8A16 leaf: int8 table, dtype activations
        return quant.wdense(p, x, dtype)
    return jnp.dot(x.astype(dtype), p["w"].astype(dtype)) + p["b"].astype(dtype)


def init_layer_norm(d: int) -> Params:
    return {
        "scale": jnp.ones((d,), dtype=jnp.float32),
        "bias": jnp.zeros((d,), dtype=jnp.float32),
    }


def layer_norm(p: Params, x: jax.Array, eps: float = 1e-6) -> jax.Array:
    # Normalize in f32 regardless of compute dtype: variance in bf16 is lossy.
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def init_attention(key: jax.Array, d_model: int, n_heads: int) -> Params:
    """QKV/out projections with an explicit head axis (shardable over tp).

    Shapes: wq/wk/wv ``[d_model, n_heads, d_head]``, wo ``[n_heads, d_head,
    d_model]`` — the head axis stays a named dimension so a tp sharding rule
    can split it without reshapes.
    """
    d_head = d_model // n_heads
    ks = jax.random.split(key, 4)
    return {
        "wq": _dense_init(ks[0], (d_model, n_heads, d_head), d_model),
        "wk": _dense_init(ks[1], (d_model, n_heads, d_head), d_model),
        "wv": _dense_init(ks[2], (d_model, n_heads, d_head), d_model),
        "wo": _dense_init(ks[3], (n_heads, d_head, d_model), d_model),
    }


def dot_product_attention(
    q: jax.Array,       # [B, H, Lq, D]
    k: jax.Array,       # [B, H, Lk, D]
    v: jax.Array,       # [B, H, Lk, D]
    mask: jax.Array,    # [B, 1|H, Lq|1, Lk] additive-mask source (1 = attend)
) -> jax.Array:
    """Masked softmax(QKᵀ)V → [B, H, Lq, D].

    Numerics/traffic contract: QKᵀ accumulates in f32 (MXU native), but the
    materialized [B, H, Lq, Lk] score array is stored in the **compute
    dtype** (bf16 on TPU) — at seq 512 / BERT-base shapes that halves the
    dominant HBM traffic of the layer and measures ~1.9× faster end-to-end
    on v5e with max rel error identical to the bf16-input baseline (0.0056
    vs f32 reference, both). Softmax statistics (exp, sum, divide) still
    run in f32; with f32 inputs the whole path is f32 and matches the old
    ``jax.nn.softmax`` form exactly.
    """
    d = q.shape[-1]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    scores = (scores / np.sqrt(d)).astype(q.dtype)
    scores = jnp.where(mask > 0, scores, jnp.asarray(NEG_INF, q.dtype))
    m = scores.max(axis=-1, keepdims=True)
    p = jnp.exp((scores - m).astype(jnp.float32))
    z = p.sum(axis=-1, keepdims=True)
    probs = (p / z).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _proj_in(leaf: Any, x: jax.Array, dtype: Any) -> jax.Array:
    """x [B, L, d] @ leaf [d, H, E] → [B, H, L, E]; int8 (W8A8) and W8A16
    paths for quantized leaves (``models.quant`` leaf conventions)."""
    from agent_tpu.models import quant

    if quant.is_quantized(leaf):
        return quant.qproj_in(leaf, x, dtype)
    if quant.is_weight_only(leaf):
        return quant.wproj_in(leaf, x, dtype)
    return jnp.einsum("bld,dhe->bhle", x.astype(dtype), leaf.astype(dtype))


def _proj_out(leaf: Any, x: jax.Array, dtype: Any) -> jax.Array:
    """x [B, H, L, E] @ leaf [H, E, d] → [B, L, d]; int8 (W8A8) and W8A16
    paths for quantized leaves."""
    from agent_tpu.models import quant

    if quant.is_quantized(leaf):
        return quant.qproj_out(leaf, x, dtype)
    if quant.is_weight_only(leaf):
        return quant.wproj_out(leaf, x, dtype)
    return jnp.einsum("bhle,hed->bld", x, leaf.astype(dtype))


def attention(
    p: Params,
    x_q: jax.Array,                 # [B, Lq, d_model]
    x_kv: jax.Array,                # [B, Lk, d_model] (== x_q for self-attn)
    mask: jax.Array,                # [B, 1, Lq|1, Lk] (1 = attend)
    dtype: Any,
    cache: Optional[Dict[str, jax.Array]] = None,
    cache_index: Optional[jax.Array] = None,
    block_table: Optional[jax.Array] = None,
    attn_fn=dot_product_attention,
) -> Tuple[jax.Array, Optional[Dict[str, jax.Array]]]:
    """Multi-head attention; optional KV cache for autoregressive decode.

    With ``cache`` (arrays ``k``/``v`` of shape [B, H, Lmax, D]) and a scalar
    ``cache_index``, the new K/V rows are written at ``cache_index`` via
    ``dynamic_update_slice`` and attention runs over the full cache — the
    static-shape decode pattern that keeps ``lax.scan`` from retracing
    (SURVEY.md §7 "hard parts": decode doesn't retrace per step).

    A **vector** ``cache_index`` ([B] int32) writes each row's K/V at its own
    position — the continuous-batching decode case (ISSUE 15), where slots in
    a running batch sit at different decode depths. The written values are
    identical to the scalar path's; only the addressing generalizes.

    With ``block_table`` ([B, MAXB] int32, ISSUE 16) the cache is a **paged
    pool**: ``k``/``v`` are ``[NB, H, BS, D]`` fixed-size blocks shared by
    every row, and row ``b``'s logical position ``p`` lives in pool block
    ``block_table[b, p // BS]`` at offset ``p % BS``. Pool block 0 is the
    trash block: unallocated/released table entries point there, so a frozen
    row's steady rewrite at its frozen position can never corrupt a block
    that was reallocated to a live request. The read view gathers the row's
    blocks and slices to the mask's key length, so the attention shapes —
    and therefore the reduction trees and the bits — match the dense path
    exactly; positions past a row's write point are masked, and
    ``exp(NEG_INF - m)`` is exactly 0.0 in f32, so trash/garbage content
    never contributes. Requires a vector ``cache_index``.

    ``attn_fn`` is the inner attention kernel — the sp ring path
    (``agent_tpu.parallel.ring.ring_attention``) substitutes here.
    """
    q = _proj_in(p["wq"], x_q, dtype)
    k = _proj_in(p["wk"], x_kv, dtype)
    v = _proj_in(p["wv"], x_kv, dtype)

    if cache is not None:
        assert cache_index is not None
        if block_table is not None:
            if getattr(cache_index, "ndim", 0) != 1:
                raise ValueError(
                    "paged KV (block_table) requires a per-row vector "
                    "cache_index"
                )
            bsz = block_table.shape[0]
            maxb = block_table.shape[1]
            bs = cache["k"].shape[2]                  # pool block size
            lk = mask.shape[-1]
            ji = cache_index // bs                    # [B] logical block
            off = cache_index % bs                    # [B] offset in block
            # Rows whose position ran past table coverage (frozen at the
            # engine's max) write to the trash block, not a clamped real one.
            blk = jnp.where(
                ji < maxb,
                jnp.take_along_axis(
                    block_table, jnp.minimum(ji, maxb - 1)[:, None], axis=1
                )[:, 0],
                0,
            )
            # Scatter one K/V row per batch row: pool[blk[b], :, off[b]] =
            # new_kv[b]. Duplicate (blk, off) pairs only ever collide at the
            # trash block (allocated blocks are row-exclusive) — harmless.
            pk = cache["k"].astype(dtype).at[blk, :, off].set(k[:, :, 0])
            pv = cache["v"].astype(dtype).at[blk, :, off].set(v[:, :, 0])

            def view(pool):
                x = pool[block_table]                 # [B, MAXB, H, BS, D]
                x = x.transpose(0, 2, 1, 3, 4)
                x = x.reshape(bsz, pool.shape[1], maxb * bs, pool.shape[3])
                return x[:, :, :lk]                   # dense-shape view

            out = attn_fn(q, view(pk), view(pv), mask)
            y = _proj_out(p["wo"], out, dtype)
            return y, {"k": pk, "v": pv}
        if getattr(cache_index, "ndim", 0) == 1:
            # Per-row positions: one decode step (Lk == 1) written to each
            # row's own cache slot. Formulated as a one-hot select, NOT a
            # gather/scatter — XLA lowers scatters to element loops on some
            # backends (measured 3× per-step cost on CPU), while the dense
            # where is a single vectorized pass over the cache.
            sel = (
                jnp.arange(cache["k"].shape[2])[None, :]
                == cache_index[:, None]
            )[:, None, :, None]                       # [B, 1, Lmax, 1]
            k = jnp.where(sel, k, cache["k"].astype(dtype))
            v = jnp.where(sel, v, cache["v"].astype(dtype))
        else:
            zero = jnp.zeros((), dtype=jnp.int32)
            k = jax.lax.dynamic_update_slice(
                cache["k"].astype(dtype), k, (zero, zero, cache_index, zero)
            )
            v = jax.lax.dynamic_update_slice(
                cache["v"].astype(dtype), v, (zero, zero, cache_index, zero)
            )
        cache = {"k": k, "v": v}

    out = attn_fn(q, k, v, mask)
    y = _proj_out(p["wo"], out, dtype)
    return y, cache


def init_ffn(key: jax.Array, d_model: int, d_ff: int) -> Params:
    k1, k2 = jax.random.split(key)
    return {"wi": init_dense(k1, d_model, d_ff), "wo": init_dense(k2, d_ff, d_model)}


def ffn(p: Params, x: jax.Array, dtype: Any) -> jax.Array:
    h = jax.nn.gelu(dense(p["wi"], x, dtype))
    return dense(p["wo"], h, dtype)


def init_block(key: jax.Array, d_model: int, n_heads: int, d_ff: int,
               cross: bool = False) -> Params:
    ks = jax.random.split(key, 3)
    p: Params = {
        "ln1": init_layer_norm(d_model),
        "attn": init_attention(ks[0], d_model, n_heads),
        "ln2": init_layer_norm(d_model),
        "ffn": init_ffn(ks[1], d_model, d_ff),
    }
    if cross:
        p["ln_x"] = init_layer_norm(d_model)
        p["xattn"] = init_attention(ks[2], d_model, n_heads)
    return p


def encoder_block(
    p: Params, x: jax.Array, mask: jax.Array, dtype: Any,
    attn_fn=dot_product_attention, moe_ctx=None, with_aux: bool = False,
):
    """Pre-LN transformer block: x + Attn(LN(x)); x + FFN(LN(x)).

    A block carrying a ``moe`` subtree (``encoder.init_params`` with
    ``moe_experts > 0``) routes its FFN sublayer through the Switch MoE
    layer; ``moe_ctx`` is the ``(MoeConfig, mesh-or-None)`` pair the caller
    (``encoder.forward``) resolved once for the whole stack.

    ``with_aux=True`` returns ``(x, aux)`` where ``aux`` is the block's
    Switch load-balancing auxiliary loss (0.0 for dense blocks) — the
    training path MUST use it for MoE configs (a router trained without
    the aux term collapses onto one expert); serving ignores it.
    """
    h = layer_norm(p["ln1"], x)
    a, _ = attention(p["attn"], h, h, mask, dtype, attn_fn=attn_fn)
    x = x + a
    h = layer_norm(p["ln2"], x)
    if "moe" in p:
        from agent_tpu.models import moe as moe_mod

        if moe_ctx is None:
            # Fail with the contract, not an unpack TypeError deep inside a
            # traced shard_map: every MoE-capable entry point must resolve
            # the (MoeConfig, mesh) pair (encoder.forward does; the pp
            # pipeline intentionally does not — pp+MoE is unsupported).
            raise ValueError(
                "encoder block has a 'moe' subtree but no moe_ctx was "
                "threaded — this forward path does not support MoE configs"
            )
        mcfg, mesh = moe_ctx
        B, L, d = h.shape
        y, aux = moe_mod.moe_ffn(
            p["moe"], h.astype(dtype).reshape(B * L, d), mcfg, mesh=mesh
        )
        out = x + y.reshape(B, L, d).astype(x.dtype)
        return (out, aux) if with_aux else out
    out = x + ffn(p["ffn"], h, dtype)
    return (out, jnp.float32(0.0)) if with_aux else out


def decoder_block(
    p: Params,
    x: jax.Array,                    # [B, Lq, d_model]
    self_mask: jax.Array,            # [B, 1, Lq|1, Lself]
    enc_out: jax.Array,              # [B, Lsrc, d_model]
    enc_mask: jax.Array,             # [B, 1, 1, Lsrc]
    dtype: Any,
    cache: Optional[Dict[str, jax.Array]] = None,
    cache_index: Optional[jax.Array] = None,
    block_table: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Optional[Dict[str, jax.Array]]]:
    h = layer_norm(p["ln1"], x)
    a, cache = attention(
        p["attn"], h, h, self_mask, dtype, cache=cache,
        cache_index=cache_index, block_table=block_table,
    )
    x = x + a
    h = layer_norm(p["ln_x"], x)
    a, _ = attention(p["xattn"], h, enc_out, enc_mask, dtype)
    x = x + a
    h = layer_norm(p["ln2"], x)
    return x + ffn(p["ffn"], h, dtype), cache


def sinusoidal_positions(length: int, d_model: int) -> np.ndarray:
    """Classic fixed sinusoidal position table [length, d_model] (f32)."""
    pos = np.arange(length)[:, None].astype(np.float64)
    dim = np.arange(0, d_model, 2)[None, :].astype(np.float64)
    angle = pos / np.power(10000.0, dim / d_model)
    table = np.zeros((length, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(angle)
    table[:, 1::2] = np.cos(angle)
    return table


def causal_mask(length: int) -> np.ndarray:
    """[1, 1, L, L] lower-triangular attend mask."""
    return np.tril(np.ones((length, length), dtype=np.int32))[None, None]


def pad_mask_to_attn(mask: jax.Array) -> jax.Array:
    """[B, L] padding mask (1 = real token) → [B, 1, 1, L] broadcastable."""
    return mask[:, None, None, :]


def is_key_padding_mask(mask: jax.Array, batch: int, lk: int) -> bool:
    """True iff ``mask`` is a key-padding attention mask ``[B|1, 1, 1, Lk]``.

    The shared contract gate of the fast attention paths (ring in
    ``agent_tpu.parallel.ring``, Pallas flash in ``agent_tpu.kernels``):
    shapes that fail it take the dense path. A contract change here changes
    every fast path at once.
    """
    return (
        mask.ndim == 4
        and mask.shape[1] == 1
        and mask.shape[2] == 1              # no causal / per-query dim
        and mask.shape[0] in (1, batch)
        and mask.shape[3] == lk
    )


def materialize_key_padding_mask(mask: jax.Array, batch: int, lk: int) -> jax.Array:
    """Broadcast a shared ``[1, 1, 1, Lk]`` mask to ``[B, 1, 1, Lk]`` — the
    sharded fast paths partition the batch dim, which a size-1 dim cannot
    satisfy."""
    if mask.shape[0] == 1 and batch > 1:
        return jnp.broadcast_to(mask, (batch, 1, 1, lk))
    return mask


def count_params(params: Params) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)))


def assign_from_npz(params: Params, path: str) -> Params:
    """Overlay a flat ``.npz`` checkpoint onto an init'd param pytree.

    Keys are dotted paths (``blocks.0.attn.wq``); leaves absent from the file
    keep their initialized values, so partial checkpoints compose with
    deterministic init. Shared by encoder and seq2seq loaders.
    """
    flat = dict(np.load(path))

    def assign(tree, prefix=""):
        if isinstance(tree, dict):
            return {k: assign(v, f"{prefix}{k}.") for k, v in tree.items()}
        if isinstance(tree, list):
            return [assign(v, f"{prefix}{i}.") for i, v in enumerate(tree)]
        key = prefix[:-1]
        return jnp.asarray(flat[key]) if key in flat else tree

    return assign(params)
