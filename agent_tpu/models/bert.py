"""HF-BERT-compatible encoder: serve *pretrained* checkpoints TPU-side.

The reference's capability story was serving pretrained weights — a compiled
artifact at a well-known path (reference ``ops/_tpu_runtime.py:23-31``) and a
hub model for summarize (``ops/map_summarize.py:29-32``). This module is that
story for the classify family: a user points ``model_path`` at a standard
Hugging Face BERT checkpoint **directory** (``config.json`` +
``pytorch_model.bin`` / ``model.safetensors`` + ``vocab.txt``) and the op
serves it — same weights, same numerics (differential-tested against
``transformers``' reference implementation), but batched, jitted, and sharded
on the mesh instead of row-at-a-time on host torch.

Architecture notes (faithful to BERT, deliberately NOT our pre-LN encoder):
post-LN residuals, learned position + token-type embeddings, erf-exact GELU,
tanh pooler over [CLS], optional sequence-classification head. The attention
core goes through the same injectable ``attn_fn`` contract as the in-house
models, so the Pallas flash kernel and ring attention compose unchanged.

No network access is assumed anywhere: checkpoints load from local disk only.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from agent_tpu.models import layers
from agent_tpu.models.layers import Params, dot_product_attention


@dataclass(frozen=True)
class BertConfig:
    """Mirror of the HF ``config.json`` fields the forward needs."""

    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab: int = 2
    layer_norm_eps: float = 1e-12
    num_labels: int = 1000
    dtype: str = "bfloat16"
    # "int8": serve with W8A8 quantized matmuls (models.quant) — execution
    # mode, not a different artifact; the checkpoint weights are quantized
    # per-channel at load. "w8a16": weight-only int8, activations at dtype.
    quant: str = "none"

    # Uniform serving-config view (the classify op reads these off any family).
    @property
    def max_len(self) -> int:
        return self.max_position

    @property
    def n_classes(self) -> int:
        return self.num_labels

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def from_hf_json(cls, path: str, **overrides) -> "BertConfig":
        try:
            with open(path) as f:
                hf = json.load(f)
        except json.JSONDecodeError as exc:
            # NOT a ValueError to callers: JSONDecodeError subclasses it, and
            # the op's soft-error handler would silently drop the shard as
            # caller bad_input. A corrupt checkpoint is a retryable
            # integrity failure, not a payload problem.
            raise RuntimeError(
                f"unreadable checkpoint config.json at {path}: {exc}"
            ) from exc
        if hf.get("model_type") not in (None, "bert"):
            raise RuntimeError(
                f"not a BERT checkpoint (model_type={hf.get('model_type')!r}"
                " — map_classify_tpu serves model_type=bert; map_summarize "
                "serves BART)"
            )
        fields = dict(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"],
            max_position=hf["max_position_embeddings"],
            type_vocab=hf.get("type_vocab_size", 2),
            layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
        )
        if "num_labels" in hf:
            fields["num_labels"] = hf["num_labels"]
        elif hf.get("id2label"):
            fields["num_labels"] = len(hf["id2label"])
        fields.update(overrides)
        return cls(**fields)


def _ln(params: Params, x: jax.Array, eps: float) -> jax.Array:
    """LayerNorm in f32 (BERT's eps differs from our in-house default)."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(axis=-1, keepdims=True)
    out = (x32 - mu) / jnp.sqrt(var + eps)
    return (out * params["scale"] + params["bias"]).astype(x.dtype)


def forward(
    params: Params,
    ids: jax.Array,        # [B, L] int32 token ids (wordpiece)
    mask: jax.Array,       # [B, L] int32 padding mask (1 = real)
    cfg: BertConfig,
    attn_fn=dot_product_attention,
) -> jax.Array:
    """Sequence-classification logits [B, num_labels] (f32).

    Matches ``transformers.BertModel`` + pooler + linear head: embeddings
    (word + learned position + token type 0) → post-LN transformer stack →
    tanh pooler over [CLS] → head. Softmax accumulation and LayerNorms run
    in f32 regardless of compute dtype.
    """
    dtype = cfg.compute_dtype
    B, L = ids.shape
    emb = params["embed"]
    x = (
        emb["word"].astype(dtype)[ids]
        + emb["pos"][:L].astype(dtype)[None]
        + emb["type"][0].astype(dtype)[None, None]
    )
    x = _ln(emb["ln"], x, cfg.layer_norm_eps)

    attn_mask = layers.pad_mask_to_attn(mask)
    d_head = cfg.hidden_size // cfg.num_heads

    def split_heads(t):
        return t.reshape(B, L, cfg.num_heads, d_head).transpose(0, 2, 1, 3)

    for blk in params["layers"]:
        a = blk["attn"]
        q = split_heads(layers.dense(a["q"], x, dtype))
        k = split_heads(layers.dense(a["k"], x, dtype))
        v = split_heads(layers.dense(a["v"], x, dtype))
        ctx = attn_fn(q, k, v, attn_mask)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, L, cfg.hidden_size)
        x = _ln(a["ln"], x + layers.dense(a["o"], ctx, dtype),
                cfg.layer_norm_eps)
        f = blk["ffn"]
        h = jax.nn.gelu(
            layers.dense(f["i"], x, dtype).astype(jnp.float32),
            approximate=False,
        ).astype(dtype)
        x = _ln(f["ln"], x + layers.dense(f["o"], h, dtype),
                cfg.layer_norm_eps)

    pooled = jnp.tanh(
        layers.dense(params["pooler"], x[:, 0], dtype).astype(jnp.float32)
    ).astype(dtype)
    logits = layers.dense(params["head"], pooled, dtype)
    return logits.astype(jnp.float32)


# ---- weight import ----


def _dense_from(sd: Dict[str, np.ndarray], prefix: str) -> Params:
    """HF ``nn.Linear`` ([out, in] weight) → our ``{"w": [in, out], "b"}``."""
    return {
        "w": np.ascontiguousarray(sd[f"{prefix}.weight"].T),
        "b": sd[f"{prefix}.bias"],
    }


def _ln_from(sd: Dict[str, np.ndarray], prefix: str) -> Params:
    return {"scale": sd[f"{prefix}.weight"], "bias": sd[f"{prefix}.bias"]}


def from_state_dict(
    sd: Dict[str, np.ndarray], cfg: BertConfig, head_seed: str = "bert-head"
) -> Params:
    """HF BERT state dict (``BertModel`` or ``BertForSequenceClassification``
    naming — the ``bert.`` prefix is stripped) → our param pytree. A missing
    classification head gets deterministic random init seeded by
    ``head_seed`` (same contract as the in-house models: same id ⇒ same
    weights)."""
    sd = {
        (k[5:] if k.startswith("bert.") else k): np.asarray(v)
        for k, v in sd.items()
    }
    params: Params = {
        "embed": {
            "word": sd["embeddings.word_embeddings.weight"],
            "pos": sd["embeddings.position_embeddings.weight"],
            "type": sd["embeddings.token_type_embeddings.weight"],
            "ln": _ln_from(sd, "embeddings.LayerNorm"),
        },
        "layers": [],
        "pooler": _dense_from(sd, "pooler.dense"),
    }
    for i in range(cfg.num_layers):
        p = f"encoder.layer.{i}"
        params["layers"].append(
            {
                "attn": {
                    "q": _dense_from(sd, f"{p}.attention.self.query"),
                    "k": _dense_from(sd, f"{p}.attention.self.key"),
                    "v": _dense_from(sd, f"{p}.attention.self.value"),
                    "o": _dense_from(sd, f"{p}.attention.output.dense"),
                    "ln": _ln_from(sd, f"{p}.attention.output.LayerNorm"),
                },
                "ffn": {
                    "i": _dense_from(sd, f"{p}.intermediate.dense"),
                    "o": _dense_from(sd, f"{p}.output.dense"),
                    "ln": _ln_from(sd, f"{p}.output.LayerNorm"),
                },
            }
        )
    # The checkpoint's trained head is used only when it matches
    # cfg.num_labels (config.json's own num_labels always does — HF writes
    # them consistently). An explicit payload override to a different label
    # space gets a fresh seeded head instead: mixing a k-clamp from the
    # override with a differently-sized trained head would crash top_k on
    # device.
    cls_w = sd.get("classifier.weight")
    if cls_w is not None and cls_w.shape[0] == cfg.num_labels:
        params["head"] = _dense_from(sd, "classifier")
    else:
        key = layers.seed_from(head_seed)
        params["head"] = layers.init_dense(
            key, cfg.hidden_size, cfg.num_labels
        )
    return params


def is_hf_dir(path: str) -> bool:
    """A local HF checkpoint directory: has ``config.json``."""
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, "config.json")
    )


def load_hf_dir(path: str, **config_overrides) -> Tuple[BertConfig, Params]:
    """Load (config, params) from a local HF BERT checkpoint directory.

    Weights: ``model.safetensors`` if present (and the safetensors package
    is importable), else ``pytorch_model.bin`` via torch (CPU map). torch
    imports lazily — only checkpoints pay its import cost.
    """
    cfg = BertConfig.from_hf_json(
        os.path.join(path, "config.json"), **config_overrides
    )
    st_path = os.path.join(path, "model.safetensors")
    bin_path = os.path.join(path, "pytorch_model.bin")
    if os.path.exists(st_path):
        try:
            from safetensors.numpy import load_file

            sd = load_file(st_path)
            return cfg, from_state_dict(sd, cfg, head_seed=path)
        except ImportError:
            pass
    if not os.path.exists(bin_path):
        raise FileNotFoundError(
            f"no model.safetensors or pytorch_model.bin under {path}"
        )
    import torch

    raw = torch.load(bin_path, map_location="cpu", weights_only=True)
    sd = {k: v.numpy() for k, v in raw.items()}
    return cfg, from_state_dict(sd, cfg, head_seed=path)


# ---- tokenizer ----

_tok_cache: Dict[str, Any] = {}
_tok_lock = threading.Lock()


def hf_wordpiece(path: str):
    """The checkpoint's wordpiece tokenizer (``vocab.txt``), with the HF
    special ids resolved from the vocab itself ([CLS]/[SEP]/[PAD]/[UNK] live
    at whatever line the file puts them). Cached per directory."""
    with _tok_lock:
        tok = _tok_cache.get(path)
        if tok is not None:
            return tok
    from agent_tpu.models.tokenizer import WordPieceTokenizer

    vocab_path = os.path.join(path, "vocab.txt")
    if not os.path.exists(vocab_path):
        raise ValueError(f"HF checkpoint {path} has no vocab.txt")
    lowercase = True
    tcfg_path = os.path.join(path, "tokenizer_config.json")
    if os.path.exists(tcfg_path):
        with open(tcfg_path) as f:
            lowercase = bool(json.load(f).get("do_lower_case", True))
    tok = WordPieceTokenizer.from_file(vocab_path, lowercase=lowercase)
    # The class-level unk_id (3) is the in-house vocab's; remap it to the
    # checkpoint's own [UNK] line so OOV words don't encode as whatever
    # token happens to sit at line 3 (bert-base: '[unused2]').
    if "[UNK]" in tok.vocab:
        tok.unk_id = tok.vocab["[UNK]"]
    with _tok_lock:
        _tok_cache[path] = tok
    return tok


def _is_cjk(cp: int) -> bool:
    """HF BasicTokenizer's CJK ranges (each char becomes its own word)."""
    return (
        0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F
    )


def basic_normalize(text: str, strip_accents: bool) -> str:
    """HF ``BasicTokenizer`` text normalization: accent stripping (NFD +
    drop combining marks — on by default when ``do_lower_case``) and CJK
    characters spaced out so each is one word. Without this, 'café' would
    miss the vocab and encode as [UNK] where transformers finds 'cafe'."""
    import unicodedata

    if strip_accents:
        text = "".join(
            c for c in unicodedata.normalize("NFD", text)
            if unicodedata.category(c) != "Mn"
        )
    if any(_is_cjk(ord(c)) for c in text):
        text = "".join(
            f" {c} " if _is_cjk(ord(c)) else c for c in text
        )
    return text


def encode_pad_batch(
    tok, texts, max_len: int, batch_buckets, length_buckets
) -> Tuple[np.ndarray, np.ndarray]:
    """[CLS] pieces [SEP] per row → (ids [B, L] int32, lengths [B] int32)
    with bucketed static shapes (same shape discipline as ``byte_encode_pad``;
    wordpiece is a Python loop — slower per row than the byte path, priced in
    by serving real vocab)."""
    from agent_tpu.models.tokenizer import bucket_length

    cls_id = tok.vocab.get("[CLS]")
    sep_id = tok.vocab.get("[SEP]")
    pad_id = tok.vocab.get("[PAD]", 0)
    if cls_id is None or sep_id is None:
        raise ValueError("vocab.txt lacks [CLS]/[SEP] tokens")
    rows = [
        [cls_id]
        + tok.encode(basic_normalize(t, tok.lowercase))[: max_len - 2]
        + [sep_id]
        for t in texts
    ]
    longest = max(len(r) for r in rows)
    L = bucket_length(min(longest, max_len), length_buckets)
    B = bucket_length(len(rows), batch_buckets)
    ids = np.full((B, L), pad_id, dtype=np.int32)
    lengths = np.zeros(B, dtype=np.int32)
    for r, row in enumerate(rows):
        if len(row) > L:
            # Bucket truncation keeps the trailing [SEP] (transformers'
            # truncation semantics), not a mid-word cut.
            row = row[: L - 1] + [sep_id]
        ids[r, : len(row)] = row
        lengths[r] = len(row)
    return ids, lengths
