"""Transformer encoder classifier — the model behind ``map_classify_tpu``.

The reference classified with an INT8 TFLite CNN on a Coral Edge TPU, one row
per ``interpreter.invoke()`` (reference ``ops/map_classify_tpu.py:71-74``,
``CONTRACT.md:24`` "No batching"). The TPU-native successor is a BERT-class
token encoder compiled once per shape bucket and run *batched* with the batch
dim sharded over the mesh ``dp`` axis (SURVEY.md §2.8) — the MXU wants large
batched matmuls, not row-at-a-time invokes.

Weights are deterministic from the model id (:func:`agent_tpu.models.layers.seed_from`)
or loaded from an ``.npz`` checkpoint path — the generalization of the
reference's immutable model artifact at ``/models/model_edgetpu.tflite``
(reference ``ops/_tpu_runtime.py:23-31``).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from agent_tpu.models import layers
from agent_tpu.models.layers import Params


@dataclass(frozen=True)
class EncoderConfig:
    """Model hyperparameters. Defaults give a ~7M-param encoder whose dims are
    multiples of the MXU tile (128) where it matters (d_model, d_ff)."""

    vocab_size: int = 260          # ByteTokenizer vocab (256 bytes + specials)
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 1024
    max_len: int = 2048            # reference profile max_tokens (app.py:108)
    n_classes: int = 1000
    dtype: str = "bfloat16"
    # "int8" runs the hot matmuls W8A8 on the MXU (models.quant) — the
    # TPU-native successor of the reference's INT8 TFLite execution
    # (reference ops/_tpu_runtime.py:23-31); "w8a16" keeps the int8 weight
    # tables but leaves activations at dtype (the memory-bound recipe).
    quant: str = "none"
    # Serving-strategy fields (payload model_config may set them, SURVEY
    # §2.8 "strategies usable by the workload"):
    # pp > 1 pipelines the block stack over a ``pp`` mesh axis
    # (parallel.pipeline.encoder_forward_pp); n_layers must divide by pp.
    pp: int = 1
    # moe_experts > 0 replaces each block's dense FFN with a Switch MoE
    # layer (models.moe) — experts shard over an ``ep`` mesh axis when the
    # serving mesh has one, else run unsharded.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    def scaled(self, **overrides) -> "EncoderConfig":
        return replace(self, **overrides)


def moe_cfg_of(cfg: EncoderConfig):
    """The block-level MoE config for an ``moe_experts > 0`` encoder."""
    from agent_tpu.models.moe import MoeConfig

    return MoeConfig(
        d_model=cfg.d_model, d_ff=cfg.d_ff, n_experts=cfg.moe_experts,
        capacity_factor=cfg.moe_capacity_factor, dtype=cfg.dtype,
    )


def init_params(cfg: EncoderConfig, model_id: str = "classify-default") -> Params:
    """Deterministic param pytree for ``model_id`` (same id ⇒ same weights).

    ``moe_experts > 0``: each block's dense ``ffn`` subtree is replaced by a
    ``moe`` subtree (router + expert-stacked FFN, ``models.moe``); attention
    and norms are unchanged, so the MoE encoder serves through the same
    forward and op contract.
    """
    key = layers.seed_from(model_id)
    ks = jax.random.split(key, cfg.n_layers + 3)
    blocks = [
        layers.init_block(ks[i + 1], cfg.d_model, cfg.n_heads, cfg.d_ff)
        for i in range(cfg.n_layers)
    ]
    if cfg.moe_experts > 0:
        from agent_tpu.models import moe

        mcfg = moe_cfg_of(cfg)
        for i, blk in enumerate(blocks):
            del blk["ffn"]
            # ks[i + 1] already differs per layer; the fold_in decorrelates
            # the MoE init from init_block's split of the SAME per-layer key
            # (the attention weights above consumed splits of ks[i + 1]).
            blk["moe"] = moe.init_moe_ffn(
                jax.random.fold_in(ks[i + 1], 0x40E), mcfg
            )
    params: Params = {
        "embed": jax.random.normal(
            ks[0], (cfg.vocab_size, cfg.d_model), dtype=jnp.float32
        ) * 0.02,
        "pos": jnp.asarray(layers.sinusoidal_positions(cfg.max_len, cfg.d_model)),
        "blocks": blocks,
        "ln_f": layers.init_layer_norm(cfg.d_model),
        "head": layers.init_dense(ks[-1], cfg.d_model, cfg.n_classes),
    }
    return params


def load_npz(path: str, cfg: EncoderConfig) -> Params:
    """Load params from a flat ``.npz`` (keys like ``blocks.0.attn.wq``)."""
    return layers.assign_from_npz(init_params(cfg, model_id=path), path)


def forward(
    params: Params,
    ids: jax.Array,      # [B, L] int32 token ids
    mask: jax.Array,     # [B, L] int32 padding mask (1 = real)
    cfg: EncoderConfig,
    attn_fn=layers.dot_product_attention,
    remat: bool = False,
    mesh=None,
    with_aux: bool = False,
):
    """Logits [B, n_classes] (f32). Mean-pool over real tokens, linear head.

    ``remat=True`` wraps each block in ``jax.checkpoint`` so the backward
    pass recomputes block activations instead of storing them — at training
    scale the stored [B, H, L, L] attention scores otherwise exceed HBM
    (BERT-base, batch 256, seq 512: ~39 GB saved for ~33% more FLOPs).

    ``mesh`` matters only for MoE configs (``moe_experts > 0``): when it
    carries an ``ep`` axis the expert batches get explicit sharding
    constraints so the experts provably land on ``ep``.

    ``with_aux=True`` returns ``(logits, aux)`` — the mean Switch
    load-balancing loss over blocks (0.0 for dense configs). Blocks return
    their aux through the (possibly checkpointed) block_fn, never via
    side-channel closures: a Python-list accumulator would leak tracers
    out of ``jax.checkpoint``'s inner trace.
    """
    dtype = cfg.compute_dtype
    L = ids.shape[1]
    x = params["embed"].astype(dtype)[ids] + params["pos"][:L].astype(dtype)[None]
    attn_mask = layers.pad_mask_to_attn(mask)
    moe_ctx = None
    if cfg.moe_experts > 0:
        moe_ctx = (
            moe_cfg_of(cfg),
            mesh if mesh is not None and "ep" in mesh.shape else None,
        )
    block_fn = lambda p, h, m: layers.encoder_block(  # noqa: E731
        p, h, m, dtype, attn_fn=attn_fn, moe_ctx=moe_ctx, with_aux=True
    )
    if remat:
        # Full-block recompute (minimum memory). Selective policies were
        # swept on v5e at BERT-base/seq-512 and lost: dots-saveable OOMs at
        # batch 256 and ties full remat at 128 (247 vs 246 ex/s); with the
        # flash-train kernel the winner is no remat at all (bench `train`).
        block_fn = jax.checkpoint(block_fn)
    aux_total = jnp.float32(0.0)
    for block in params["blocks"]:
        x, aux = block_fn(block, x, attn_mask)
        aux_total = aux_total + aux
    x = layers.layer_norm(params["ln_f"], x)
    denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1).astype(jnp.float32)
    pooled = (x.astype(jnp.float32) * mask[:, :, None]).sum(axis=1) / denom
    logits = layers.dense(params["head"], pooled.astype(dtype), dtype)
    logits = logits.astype(jnp.float32)
    if with_aux:
        return logits, aux_total / max(1, cfg.n_layers)
    return logits


def topk_probs(logits: jax.Array, k: int):
    """On-device top-k over softmax probabilities → (values, indices), both
    ``[B, k]`` — the host fetches k numbers per row instead of the full
    ``[B, n_classes]`` logits; the device→host transfer is the expensive hop
    (SURVEY.md §3.2 rebuild mapping)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jax.lax.top_k(probs, k)


def topk_rows(values: np.ndarray, indices: np.ndarray) -> list:
    """Device (values, indices) → per-row [{"index", "score"}] result shape
    (reference ``ops/map_classify_tpu.py:76-82``). lax.top_k returns sorted
    descending already. ``tolist()`` first: it converts to native Python
    numbers in C, ~5× faster than per-element numpy scalar indexing at
    bench batch sizes."""
    return [
        [{"index": i, "score": s} for i, s in zip(idx_row, val_row)]
        for idx_row, val_row in zip(
            np.asarray(indices).tolist(), np.asarray(values).tolist()
        )
    ]


