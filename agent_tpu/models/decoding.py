"""Shared autoregressive decode engines: greedy and beam scans.

One ``lax.scan`` program per decode (static step count, no per-step retrace,
KV caches threaded through the carry) — the pattern SURVEY.md §7 calls the
hard part of decode-under-jit. The model supplies a step function and its
caches; the engine supplies the control flow, EOS bookkeeping, and (for
beam) the joint top-K + cache reordering. Both the in-house seq2seq family
and the imported BART family run on these engines, so generation semantics
can never drift between families.

``step_fn(tok [B], step scalar, caches) -> (logits [B, V] f32, caches)``.
"""

from __future__ import annotations

import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agent_tpu.models.layers import NEG_INF

StepFn = Callable[[jax.Array, jax.Array, Any], Tuple[jax.Array, Any]]

# The continuous engine's per-row step: positions are a [rows] vector (each
# running-batch slot sits at its own decode depth) and the encoder state is
# an argument (slots join with their own prefill output).
PositionalStepFn = Callable[
    [jax.Array, jax.Array, Any, jax.Array, jax.Array], Tuple[jax.Array, Any]
]


class KVPoolExhausted(Exception):
    """A request's worst-case KV reservation exceeds the whole pool: it can
    NEVER be seated, no matter how long it waits — the serving layer's 429.
    (A request that merely has to wait for blocks stays in the backlog; the
    engine reserves a request's full ``ceil(limit / block_size)`` blocks per
    beam row at seat time, so a seated request can never run out of blocks
    mid-decode and is never forced to emit a wrong token.)"""



def _ban_eos_before(scores, step, min_length: int, eos_id: int):
    """HF ``MinLengthLogitsProcessor``: EOS masked to ``NEG_INF`` while the
    decoder sequence (start token + generated, HF's counting = step+1) is
    below ``min_length``. Single-sourced so greedy and beam can never drift.
    ``scores``: [..., V] logits or logprobs."""
    if min_length <= 0:
        return scores
    v = scores.shape[-1]
    lead = (1,) * (scores.ndim - 1)
    return jnp.where(
        (step + 1 < min_length)
        & (jnp.arange(v) == eos_id).reshape(lead + (v,)),
        NEG_INF, scores,
    )


def _ban_eos_before_rows(scores, pos, min_length: int, eos_id: int):
    """Per-row variant of :func:`_ban_eos_before` for the continuous engine:
    ``scores`` [S, ..., V], ``pos`` [S] per-slot step indices. Same masking
    values per row as the scalar version at that row's step."""
    if min_length <= 0:
        return scores
    v = scores.shape[-1]
    cond = (pos + 1 < min_length).reshape(
        (scores.shape[0],) + (1,) * (scores.ndim - 1)
    )
    return jnp.where(
        cond & (jnp.arange(v) == eos_id).reshape(
            (1,) * (scores.ndim - 1) + (v,)
        ),
        NEG_INF, scores,
    )


def _bank_hypotheses(K: int, fin_scores, fin_toks, cand_norm, cand_toks):
    """Merge candidate hypotheses into the K-slot finished store (shared by
    ``beam_scan`` and the continuous engine so banking can never drift).
    ``cand_norm`` [B, n] (``-inf`` = ineligible — it must be -inf, see the
    ``beam_scan`` initializer note), ``cand_toks`` [B, n, T]."""
    all_scores = jnp.concatenate([fin_scores, cand_norm], axis=1)
    all_toks = jnp.concatenate([fin_toks, cand_toks], axis=1)
    new_scores, sel = jax.lax.top_k(all_scores, K)          # [B, K]
    new_toks = jnp.take_along_axis(all_toks, sel[:, :, None], axis=1)
    return new_scores, new_toks


def greedy_scan(
    step_fn: StepFn,
    caches: Any,
    batch: int,
    max_new_tokens: int,
    *,
    start_id: int,
    eos_id: int,
    pad_id: int = 0,
    min_length: int = 0,
    forced_first_id: Optional[int] = None,
    forced_last_id: Optional[int] = None,
    early_exit: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy decode → (tokens [B, T], lengths [B]).

    Rows emit ``pad_id`` after their EOS; ``forced_first_id`` (e.g. BART's
    ``forced_bos_token_id``) overrides the step-0 argmax, and
    ``forced_last_id`` (``forced_eos_token_id``) the final step's, when set.
    ``min_length`` bans EOS while the sequence (decoder start + generated,
    HF's counting) is shorter — HF ``MinLengthLogitsProcessor``; a forced
    last token still wins, matching HF's processor order.

    ``early_exit=True`` (default) runs the decode as a ``lax.while_loop``
    that stops once EVERY row has emitted EOS — identical outputs (the
    untouched tail of the token buffer is already ``pad_id``, exactly what
    the full-length scan would write), but a batch of short summaries pays
    for its longest row, not for ``max_new_tokens``. ``False`` keeps the
    fixed-trip ``lax.scan`` (marginally better for batches that always run
    full length, and the differentiable choice if a scoring path ever
    backprops through decode — ``while_loop`` has no reverse rule).
    """
    bos = jnp.full((batch,), start_id, dtype=jnp.int32)
    done0 = jnp.zeros((batch,), dtype=jnp.bool_)
    last = max_new_tokens - 1

    def step_tok(tok, done, caches, step):
        logits, caches = step_fn(tok, step, caches)
        logits = _ban_eos_before(logits, step, min_length, eos_id)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if forced_first_id is not None:
            nxt = jnp.where(step == 0, jnp.int32(forced_first_id), nxt)
        if forced_last_id is not None:
            nxt = jnp.where(step == last, jnp.int32(forced_last_id), nxt)
        nxt = jnp.where(done, jnp.full_like(nxt, pad_id), nxt)
        return nxt, done | (nxt == eos_id), caches

    if early_exit:
        toks0 = jnp.full((batch, max_new_tokens), pad_id, dtype=jnp.int32)

        def cond(carry):
            step, _, done, _, _ = carry
            return jnp.logical_and(step < max_new_tokens, ~jnp.all(done))

        def body(carry):
            step, tok, done, toks, caches = carry
            nxt, done, caches = step_tok(tok, done, caches, step)
            toks = toks.at[:, step].set(nxt)
            return step + 1, nxt, done, toks, caches

        _, _, _, toks, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), bos, done0, toks0, caches)
        )
    else:
        def body(carry, step):
            tok, done, caches = carry
            nxt, done, caches = step_tok(tok, done, caches, step)
            return (nxt, done, caches), nxt

        (_, _, _), toks = jax.lax.scan(
            body, (bos, done0, caches),
            jnp.arange(max_new_tokens, dtype=jnp.int32),
        )
        toks = toks.T  # [B, T]
    lengths = jnp.sum((toks != pad_id) & (toks != eos_id), axis=1)
    return toks, lengths


def beam_scan(
    step_fn: StepFn,
    caches: Any,
    batch: int,
    vocab_size: int,
    max_new_tokens: int,
    *,
    num_beams: int,
    start_id: int,
    eos_id: int,
    pad_id: int = 0,
    length_penalty: float = 1.0,
    early_stopping: bool = False,
    min_length: int = 0,
    forced_first_id: Optional[int] = None,
    forced_last_id: Optional[int] = None,
    cache_reorder: str = "delta",
) -> Tuple[jax.Array, jax.Array]:
    """Beam-search decode → (tokens [B, T], lengths [B]); static shapes.

    HF ``BeamSearchScorer`` semantics, differential-tested token-exact
    against ``transformers`` beam generation (tests/test_bart.py; the
    engine-level invariants — beam1 == greedy, determinism, score
    dominance — live in tests/test_map_summarize.py): each step takes the
    top-2K candidates of
    the joint ``[B, K·V]`` scores; EOS candidates ranked < K bank their
    hypothesis into a static K-slot finished store (normalized by HF's
    length convention — sequence length INCLUDING the decoder start, i.e.
    ``(step+1) ** length_penalty``); the K best non-EOS candidates continue
    (gathering the KV caches along the beam axis). A row stops improving
    once its store holds K hypotheses and — with ``early_stopping=False``,
    the HF default — the best running candidate can no longer beat the
    worst banked one; ``early_stopping=True`` stops at K banked outright.
    After the scan, still-running beams of unfinished rows are banked at
    full length, and each row emits its best hypothesis.

    Beams flatten into the batch dim, so the model's step executable is
    shared with greedy at ``B*K`` rows. ``num_beams=1`` degenerates to
    greedy-with-banking: same emitted tokens as ``greedy_scan``.

    ``cache_reorder`` picks the KV-cache beam-reorder scheme, bit-identical
    outputs either way (regression-tested):

    - ``"delta"`` (default): the per-step gather of every KV cache along the
      beam axis runs under ``lax.cond``, skipped entirely on steps where the
      selected continuation is the identity permutation (each beam extends
      its own parent — ``beam_idx == arange(K)`` for every row, the common
      case once beam frontiers stabilize and for frozen rows). The gather
      moves the FULL [B·K, H, T, D] cache per layer; skipping identity steps
      removes that HBM round trip from most of a long decode.
    - ``"gather"``: the unconditional per-step gather (the pre-delta
      behavior), kept as the equivalence-test reference.
    """
    if cache_reorder not in ("delta", "gather"):
        raise ValueError(
            f"cache_reorder must be 'delta' or 'gather', got {cache_reorder!r}"
        )
    B, K, V, T = batch, num_beams, vocab_size, max_new_tokens
    K2 = 2 * K
    tok0 = jnp.full((B * K,), start_id, dtype=jnp.int32)
    # Step 0: all K beams are identical, so only beam 0 may survive top-K.
    scores0 = jnp.tile(
        jnp.array([0.0] + [NEG_INF] * (K - 1), dtype=jnp.float32), (B, 1)
    )
    toks0 = jnp.full((B, K, T), pad_id, dtype=jnp.int32)
    # Empty finished slots are -inf, NOT the finite NEG_INF: with a negative
    # length_penalty a real hypothesis can normalize below -1e9, and an
    # empty all-pad slot must never outrank a real hypothesis.
    _EMPTY = jnp.float32(-jnp.inf)
    fin_scores0 = jnp.full((B, K), _EMPTY, dtype=jnp.float32)  # normalized
    fin_toks0 = jnp.full((B, K, T), pad_id, dtype=jnp.int32)
    row_done0 = jnp.zeros((B,), dtype=jnp.bool_)
    forced_only = (
        jnp.full((V,), NEG_INF, dtype=jnp.float32).at[forced_first_id].set(0.0)
        if forced_first_id is not None
        else None
    )
    forced_last = (
        jnp.full((V,), NEG_INF, dtype=jnp.float32).at[forced_last_id].set(0.0)
        if forced_last_id is not None
        else None
    )
    lp = jnp.float32(length_penalty)

    def bank(fin_scores, fin_toks, cand_norm, cand_toks):
        """``_bank_hypotheses`` at this decode's K (see module level)."""
        return _bank_hypotheses(K, fin_scores, fin_toks, cand_norm, cand_toks)

    def body(carry, step):
        tok, scores, toks, fin_scores, fin_toks, row_done, caches = carry
        logits, caches = step_fn(tok, step, caches)   # [B*K, V]
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, V)
        # Applied BEFORE the forced substitutions, which replace the whole
        # distribution — HF's processor order, so a forced EOS wins.
        logp = _ban_eos_before(logp, step, min_length, eos_id)
        if forced_only is not None:
            logp = jnp.where(step == 0, forced_only[None, None, :], logp)
        if forced_last is not None:
            logp = jnp.where(step == T - 1, forced_last[None, None, :], logp)
        flat = (scores[:, :, None] + logp).reshape(B, K * V)
        cand_scores, idx = jax.lax.top_k(flat, K2)    # [B, 2K]
        cand_beam = idx // V                          # [B, 2K] parent beam
        cand_tok = (idx % V).astype(jnp.int32)
        is_eos = cand_tok == eos_id

        # --- bank EOS candidates (HF: only ranks < K are eligible, and
        # only while the row is still open). Hypothesis length follows
        # HF's convention: decoder start + step generated tokens, the EOS
        # itself excluded from the count → (step + 1).
        hyp_len = (step + 1).astype(jnp.float32)
        eligible = is_eos & (jnp.arange(K2)[None, :] < K) & ~row_done[:, None]
        cand_norm = jnp.where(
            eligible, cand_scores / hyp_len ** lp, _EMPTY
        )
        # Candidate token buffers: parent prefix + EOS written at `step`.
        par_toks = jnp.take_along_axis(toks, cand_beam[:, :, None], axis=1)
        eos_col = jnp.full((B, K2, 1), eos_id, dtype=jnp.int32)
        cand_toks = jax.lax.dynamic_update_slice(par_toks, eos_col,
                                                 (0, 0, step))
        fin_scores, fin_toks = bank(fin_scores, fin_toks, cand_norm,
                                    cand_toks)

        # --- continue with the K best non-EOS candidates. cand_scores are
        # already sorted descending and top_k tie-breaks by index, so this
        # masked top_k returns the first K non-EOS columns in score order.
        # EOS appears at most once per parent beam → at most K of the 2K
        # candidates are EOS → K non-EOS always exist, except at a
        # forced-last step (all mass on EOS) where the selection is
        # irrelevant: the scan ends and every row's store just filled.
        _, gather_pos = jax.lax.top_k(
            jnp.where(is_eos, -jnp.inf, cand_scores), K
        )
        new_scores = jnp.take_along_axis(cand_scores, gather_pos, axis=1)
        new_tok = jnp.take_along_axis(cand_tok, gather_pos, axis=1)
        beam_idx = jnp.take_along_axis(cand_beam, gather_pos, axis=1)

        # Rows already done freeze: emit pad, scores frozen, and the beams
        # keep THEIR OWN slots (identity, not collapse-to-beam-0): a done
        # row's running beams never reach the output (their final-bank
        # normalization is _EMPTY), so any permutation is output-equivalent
        # — identity is the one that lets the delta reorder below skip the
        # cache gather for frozen rows.
        arange_k = jnp.arange(K, dtype=jnp.int32)[None, :]
        new_scores = jnp.where(row_done[:, None], scores, new_scores)
        new_tok = jnp.where(row_done[:, None], pad_id, new_tok)
        beam_idx = jnp.where(row_done[:, None], arange_k, beam_idx)

        toks = jnp.take_along_axis(toks, beam_idx[:, :, None], axis=1)
        toks = jax.lax.dynamic_update_slice(
            toks, new_tok[:, :, None], (0, 0, step)
        )  # frozen rows write pad over pad — a no-op by construction

        # --- HF is_done: store full AND (early_stopping, or the best
        # RUNNING beam — EOS candidates excluded, HF's
        # `_check_early_stop_heuristic` uses the post-selection running
        # scores — can no longer beat the banked worst under the
        # current-length normalization).
        full = jnp.isfinite(fin_scores[:, K - 1])
        if early_stopping:
            newly_done = full
        else:
            best_running = new_scores[:, 0] / hyp_len ** lp
            newly_done = full & (best_running <= fin_scores[:, K - 1])
        row_done = row_done | newly_done

        def reorder(c):
            x = c.reshape(B, K, *c.shape[1:])
            ix = beam_idx.reshape(B, K, *([1] * (c.ndim - 1)))
            return jnp.take_along_axis(x, ix, axis=1).reshape(c.shape)

        def reorder_all(cs):
            return jax.tree_util.tree_map(reorder, cs)

        if cache_reorder == "gather":
            caches = reorder_all(caches)
        else:
            # Delta reorder: gather only when some beam actually switches
            # parent. The identity branch is a pass-through lax.cond arm —
            # no [B·K, H, T, D] gather, no HBM round trip — and shapes stay
            # scan-stable because both arms return the same pytree.
            caches = jax.lax.cond(
                jnp.all(beam_idx == arange_k),
                lambda cs: cs, reorder_all, caches,
            )
        return (
            new_tok.reshape(B * K), new_scores, toks,
            fin_scores, fin_toks, row_done, caches,
        ), None

    # while_loop, not scan: once every row is done further steps are pure
    # frozen no-ops, so a batch of short summaries pays for its longest
    # row, not for max_new_tokens — the same early exit greedy_scan makes.
    # (Nothing backprops through beam decode, so the missing reverse rule
    # costs nothing.)
    def cond(carry):
        return jnp.logical_and(carry[0] < T, ~jnp.all(carry[6]))

    def wbody(carry):
        step = carry[0]
        new_carry, _ = body(carry[1:], step)
        return (step + 1,) + new_carry

    (_, _, scores, toks, fin_scores, fin_toks, row_done, _) = (
        jax.lax.while_loop(
            cond, wbody,
            (jnp.int32(0), tok0, scores0, toks0,
             fin_scores0, fin_toks0, row_done0, caches),
        )
    )

    # Finalize (HF): rows that never closed bank their running beams,
    # normalized by their GENERATED length T — HF's unified rule is
    # "normalize by the hypothesis's generated token count" (an in-scan
    # banked hypothesis has step generated tokens + its EOS = step+1;
    # a run-to-the-end beam has exactly T).
    run_norm = jnp.where(
        row_done[:, None], _EMPTY,
        scores / jnp.float32(T) ** lp,
    )
    fin_scores, fin_toks = bank(fin_scores, fin_toks, run_norm, toks)

    out = fin_toks[:, 0]                                        # [B, T]
    out_len = jnp.sum((out != pad_id) & (out != eos_id), axis=1)
    return out, out_len


# ---------------------------------------------------------------------------
# Iteration-level continuous batching (ISSUE 15)
# ---------------------------------------------------------------------------

class DecodeTicket:
    """One request's seat in the continuous engine: the prefill handoff in,
    the emitted tokens (and TTFT/occupancy bookkeeping) out.

    Per-slot lifecycle telemetry (ISSUE 17): beyond the admit/join/first-
    token/done walls the ticket records how long it waited on KV-block
    availability (``kv_wait_s`` — the paged pool's FIFO head-of-line wait),
    the engine step count at join, the running-batch occupancy the moment
    it was seated, and an ordered ``events`` list of ``(name, wall)``
    lifecycle stamps (``admit``/``kv_wait``/``seat``/``first_token``/
    ``exit``) for the request trace."""

    __slots__ = (
        "data", "limit", "enc_row", "mask_row", "slot",
        "admitted_wall", "joined_wall", "first_token_wall", "done_wall",
        "tokens", "length", "steps",
        "kv_wait_start", "kv_wait_s", "join_step", "occupancy_at_join",
        "events",
    )

    def __init__(self, enc_row, mask_row, limit: int, data: Any = None):
        self.data = data
        self.limit = int(limit)
        self.enc_row = enc_row
        self.mask_row = mask_row
        self.slot: Optional[int] = None
        self.admitted_wall: Optional[float] = None
        self.joined_wall: Optional[float] = None
        self.first_token_wall: Optional[float] = None
        self.done_wall: Optional[float] = None
        self.tokens: Optional[np.ndarray] = None
        self.length: int = 0
        self.steps: int = 0
        self.kv_wait_start: Optional[float] = None
        self.kv_wait_s: float = 0.0
        self.join_step: int = 0
        self.occupancy_at_join: int = 0
        self.events: List[Tuple[str, float]] = []


class ContinuousBatcher:
    """Iteration-level continuous batching over a fixed-capacity slot batch.

    The scan engines above compile ONE program per decode: a batch enters
    together and (early exit aside) pays for its slowest row. Serving traffic
    is the opposite shape — requests arrive continuously — so this engine
    keeps a *running* batch of ``slots`` requests (× ``num_beams`` beam rows
    each) and drives ONE jitted step program per decode iteration:

    - finished sequences **exit between steps** (their slot frees the moment
      the per-slot done flag trips — EOS/banked-full for beam, EOS or the
      per-slot token ``limit`` for greedy);
    - queued sequences **join between steps** via a jitted slot-insertion
      (``dynamic_update_slice`` of the new request's prefill output + a
      zeroed KV block — the same delta-style "touch only what changed"
      discipline as the PR 1 cache reorder, so a join never rewrites the
      running batch);
    - every slot carries its own position vector, so the decode math per
      slot is bit-identical to a solo ``greedy_scan``/``beam_scan`` of that
      request (regression-tested in tests/test_serving.py).

    Prefill is NOT this engine's job: callers encode (batched, as its own
    step — the ``summarize_mpmd`` encoded handoff) and admit
    ``(enc_row, mask_row)`` per request. ``step_fn`` is a
    :data:`PositionalStepFn` (e.g. ``seq2seq.make_positional_step``).

    Host loop by design: one jitted step per iteration, state threaded
    through with buffer donation where the backend supports it. That trades
    the scan engines' zero host round-trips for the ability to mutate batch
    membership — the defining trade of continuous-batching serving stacks.
    """

    def __init__(
        self,
        step_fn: PositionalStepFn,
        cache_factory: Callable[[int], Any],
        *,
        slots: int,
        vocab_size: int,
        max_tokens: int,
        enc_len: int,
        d_model: int,
        start_id: int,
        eos_id: int,
        pad_id: int = 0,
        num_beams: int = 1,
        min_length: int = 0,
        length_penalty: float = 1.0,
        early_stopping: bool = False,
        cache_reorder: str = "delta",
        enc_dtype: Any = jnp.float32,
        micro_steps: int = 1,
        clock: Callable[[], float] = time.time,
    ) -> None:
        if slots < 1:
            raise ValueError("slots must be >= 1")
        if num_beams < 1:
            raise ValueError("num_beams must be >= 1")
        if micro_steps < 1:
            raise ValueError("micro_steps must be >= 1")
        if cache_reorder not in ("delta", "gather"):
            raise ValueError(
                f"cache_reorder must be 'delta' or 'gather', "
                f"got {cache_reorder!r}"
            )
        self.step_fn = step_fn
        self.slots = int(slots)
        self.K = int(num_beams)
        self.V = int(vocab_size)
        self.T = int(max_tokens)
        self.enc_len = int(enc_len)
        self.start_id = int(start_id)
        self.eos_id = int(eos_id)
        self.pad_id = int(pad_id)
        self.min_length = int(min_length)
        self.length_penalty = float(length_penalty)
        self.early_stopping = bool(early_stopping)
        self.cache_reorder = cache_reorder
        self.beam = self.K > 1
        # Decode iterations fused per dispatch: 1 (default) is pure
        # iteration-level batching — membership can change between every
        # step. Dispatch-overhead-bound deployments (small models, CPU
        # smoke, tunneled chips) raise it: N iterations run as one jitted
        # ``fori_loop`` program (XLA reuses buffers across the chained
        # updates, recovering most of the scan engines' zero-overhead
        # stepping), and joins/exits happen between CHUNKS — completed
        # slots ride out the remainder of a chunk frozen, exactly like
        # empty slots, so per-request outputs are unchanged.
        self.micro_steps = int(micro_steps)
        self._clock = clock
        S, K, T, R = self.slots, self.K, self.T, self.slots * self.K
        # State is split DYNAMIC vs STATIC: the jitted step returns only the
        # dynamic part, so per-iteration buffer traffic on backends without
        # donation (CPU) excludes the encoder block and per-slot limits —
        # they change only at joins, through the insert program.
        caches = cache_factory(R)
        # Paged KV (ISSUE 16), detected structurally from the factory's
        # pytree (``make_paged_cache_factory``): layer caches are shared
        # block pools addressed through a per-row block table. The device
        # side is pure dataflow; allocation lives HERE, on the host — a
        # numpy table mirror plus a free list, pushed to the device (one
        # tiny [R, MAXB] int32 upload) whenever seats/releases change it.
        self.paged = isinstance(caches, dict) and "table" in caches
        if self.paged:
            table = caches["table"]
            if table.shape[0] != R:
                raise ValueError(
                    f"paged cache table has {table.shape[0]} rows, engine "
                    f"needs slots*num_beams={R}"
                )
            self.kv_block_size = int(caches["layers"][0]["k"].shape[2])
            self.kv_max_blocks = int(table.shape[1])
            self.kv_pool_blocks = int(caches["layers"][0]["k"].shape[0])
            self._table_np = np.zeros(
                (R, self.kv_max_blocks), dtype=np.int32
            )
            # Block 0 is the trash block: released/unallocated table entries
            # point there so frozen rows' steady rewrites at their final
            # position can never corrupt a reallocated block.
            self._free_blocks: List[int] = list(
                range(1, self.kv_pool_blocks)
            )
            self._slot_blocks: Dict[int, List[int]] = {}
            self._table_dirty = False
        dyn: Dict[str, Any] = {
            "tok": jnp.full((R,), self.start_id, dtype=jnp.int32),
            "pos": jnp.zeros((S,), dtype=jnp.int32),
            # Empty slots are frozen rows (`row_done`): they ride every step
            # as pads + identity reorders and reset on insertion.
            "row_done": jnp.ones((S,), dtype=jnp.bool_),
            "caches": caches,
        }
        if self.beam:
            dyn["scores"] = jnp.tile(
                jnp.array([0.0] + [NEG_INF] * (K - 1), dtype=jnp.float32),
                (S, 1),
            )
            dyn["toks"] = jnp.full((S, K, T), self.pad_id, dtype=jnp.int32)
            dyn["fin_scores"] = jnp.full(
                (S, K), -jnp.inf, dtype=jnp.float32
            )
            dyn["fin_toks"] = jnp.full(
                (S, K, T), self.pad_id, dtype=jnp.int32
            )
        else:
            dyn["toks"] = jnp.full((S, T), self.pad_id, dtype=jnp.int32)
        self._dyn = dyn
        self._stat: Dict[str, Any] = {
            "limit": jnp.ones((S,), dtype=jnp.int32),
            "enc_out": jnp.zeros((R, self.enc_len, d_model), dtype=enc_dtype),
            "enc_mask": jnp.zeros((R, self.enc_len), dtype=jnp.int32),
        }
        # Buffer donation makes the step/insert updates in-place on backends
        # that support it; CPU copies and warns — silence the known-benign
        # warning rather than fork the code path.
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        step_impl = self._step_beam if self.beam else self._step_greedy
        if self.micro_steps > 1:
            n = self.micro_steps

            def chunk(dyn, stat):
                return jax.lax.fori_loop(
                    0, n, lambda _i, d: step_impl(d, stat), dyn
                )

            self._jstep = jax.jit(chunk, donate_argnums=0)
        else:
            self._jstep = jax.jit(step_impl, donate_argnums=0)
        self._jinsert = jax.jit(self._insert, donate_argnums=(0, 1))
        self._live: Dict[int, DecodeTicket] = {}
        self._free: List[int] = list(range(S))
        self._backlog: List[DecodeTicket] = []
        # Occupancy accounting (the `serve_batch_occupancy` gauge feed).
        self.steps_run = 0
        self.occupancy_sum = 0
        self.max_occupancy = 0
        self.tokens_emitted = 0

    # ---- jitted programs ----

    def _step_greedy(
        self, state: Dict[str, Any], stat: Dict[str, Any]
    ) -> Dict[str, Any]:
        S, T = self.slots, self.T
        pos, row_done = state["pos"], state["row_done"]
        logits, caches = self.step_fn(
            state["tok"], pos, state["caches"],
            stat["enc_out"], stat["enc_mask"],
        )
        logits = _ban_eos_before_rows(
            logits, pos, self.min_length, self.eos_id
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(row_done, jnp.int32(self.pad_id), nxt)
        # Frozen slots write out of bounds → dropped (their buffers must
        # survive untouched until the host extracts / the slot reseats).
        col = jnp.where(row_done, jnp.int32(T), pos)
        toks = state["toks"].at[jnp.arange(S), col].set(nxt, mode="drop")
        new_pos = jnp.where(row_done, pos, pos + 1)
        new_done = row_done | (nxt == self.eos_id) | (new_pos >= stat["limit"])
        return dict(
            state, tok=nxt, pos=new_pos, row_done=new_done, toks=toks,
            caches=caches,
        )

    def _step_beam(
        self, state: Dict[str, Any], stat: Dict[str, Any]
    ) -> Dict[str, Any]:
        """One continuous-batching beam step — ``beam_scan``'s body with the
        scalar step replaced by the per-slot ``pos`` vector, plus the
        per-slot limit banking the scan engine does after its loop."""
        S, K, V, T = self.slots, self.K, self.V, self.T
        K2 = 2 * K
        lp = jnp.float32(self.length_penalty)
        _EMPTY = jnp.float32(-jnp.inf)
        pos, row_done = state["pos"], state["row_done"]
        scores, toks = state["scores"], state["toks"]
        fin_scores, fin_toks = state["fin_scores"], state["fin_toks"]

        pos_rows = jnp.repeat(pos, K)
        logits, caches = self.step_fn(
            state["tok"], pos_rows, state["caches"],
            stat["enc_out"], stat["enc_mask"],
        )
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(S, K, V)
        logp = _ban_eos_before_rows(logp, pos, self.min_length, self.eos_id)
        flat = (scores[:, :, None] + logp).reshape(S, K * V)
        cand_scores, idx = jax.lax.top_k(flat, K2)    # [S, 2K]
        cand_beam = idx // V
        cand_tok = (idx % V).astype(jnp.int32)
        is_eos = cand_tok == self.eos_id

        # Bank EOS candidates (HF: ranks < K, open rows only); hypothesis
        # length is per-slot now — (pos + 1) generated tokens incl. the EOS's
        # predecessor, the same counting beam_scan uses.
        hyp_len = (pos + 1).astype(jnp.float32)       # [S]
        eligible = (
            is_eos & (jnp.arange(K2)[None, :] < K) & ~row_done[:, None]
        )
        cand_norm = jnp.where(
            eligible, cand_scores / hyp_len[:, None] ** lp, _EMPTY
        )
        par_toks = jnp.take_along_axis(toks, cand_beam[:, :, None], axis=1)
        col = jnp.where(row_done, jnp.int32(T), pos)  # frozen → dropped write
        cand_toks = par_toks.at[jnp.arange(S), :, col].set(
            jnp.int32(self.eos_id), mode="drop"
        )
        fin_scores, fin_toks = _bank_hypotheses(
            K, fin_scores, fin_toks, cand_norm, cand_toks
        )

        # Continue with the K best non-EOS candidates (see beam_scan for why
        # K always exist); frozen slots keep their own beams (identity).
        _, gather_pos = jax.lax.top_k(
            jnp.where(is_eos, -jnp.inf, cand_scores), K
        )
        new_scores = jnp.take_along_axis(cand_scores, gather_pos, axis=1)
        new_tok = jnp.take_along_axis(cand_tok, gather_pos, axis=1)
        beam_idx = jnp.take_along_axis(cand_beam, gather_pos, axis=1)
        arange_k = jnp.arange(K, dtype=jnp.int32)[None, :]
        new_scores = jnp.where(row_done[:, None], scores, new_scores)
        new_tok = jnp.where(
            row_done[:, None], jnp.int32(self.pad_id), new_tok
        )
        beam_idx = jnp.where(row_done[:, None], arange_k, beam_idx)

        toks = jnp.take_along_axis(toks, beam_idx[:, :, None], axis=1)
        toks = toks.at[jnp.arange(S), :, col].set(new_tok, mode="drop")

        # HF is_done, per slot (beam_scan's rule verbatim).
        full = jnp.isfinite(fin_scores[:, K - 1])
        if self.early_stopping:
            newly_done = full
        else:
            best_running = new_scores[:, 0] / hyp_len ** lp
            newly_done = full & (best_running <= fin_scores[:, K - 1])
        row_done2 = row_done | newly_done

        # Per-slot limit: a slot that ran out of budget banks its running
        # beams normalized by its OWN generated length — exactly the
        # post-loop banking a solo beam_scan(max_new=limit) performs.
        new_pos = jnp.where(row_done, pos, pos + 1)
        reached = (new_pos >= stat["limit"]) & ~row_done2
        run_norm = jnp.where(
            reached[:, None],
            new_scores / stat["limit"].astype(jnp.float32)[:, None] ** lp,
            _EMPTY,
        )
        fin_scores, fin_toks = _bank_hypotheses(
            K, fin_scores, fin_toks, run_norm, toks
        )
        row_done2 = row_done2 | reached

        def reorder(c):
            x = c.reshape(S, K, *c.shape[1:])
            ix = beam_idx.reshape(S, K, *([1] * (c.ndim - 1)))
            return jnp.take_along_axis(x, ix, axis=1).reshape(c.shape)

        def reorder_all(cs):
            return jax.tree_util.tree_map(reorder, cs)

        def reorder_paged(cs):
            # Paged beam reorder: blocks are row-exclusive (two sibling
            # beams must be free to diverge after inheriting one parent),
            # so the reorder COPIES the parent rows' block contents into
            # each child row's own blocks — the table itself is unchanged.
            # Logical block j of child row r gets logical block j of its
            # parent row: the same positions a dense row-gather would move.
            # Unallocated entries copy trash→trash (all dst duplicates land
            # on block 0, whose content is never attended unmasked).
            table = cs["table"]
            parent = (
                jnp.arange(S, dtype=jnp.int32)[:, None] * K + beam_idx
            ).reshape(-1)                              # [S*K] parent rows
            src = jnp.take(table, parent, axis=0).reshape(-1)
            dst = table.reshape(-1)

            def copy_pool(c):
                return c.at[dst].set(jnp.take(c, src, axis=0))

            return {
                "table": table,
                "layers": [
                    {"k": copy_pool(lc["k"]), "v": copy_pool(lc["v"])}
                    for lc in cs["layers"]
                ],
            }

        reorder_fn = reorder_paged if self.paged else reorder_all
        if self.cache_reorder == "gather":
            caches = reorder_fn(caches)
        else:
            # Delta reorder (PR 1): frozen/empty slots are identity, so a
            # steady-state running batch frequently skips the full-cache
            # gather — the property that keeps joins cheap.
            caches = jax.lax.cond(
                jnp.all(beam_idx == arange_k),
                lambda cs: cs, reorder_fn, caches,
            )
        return dict(
            state, tok=new_tok.reshape(S * K), pos=new_pos,
            row_done=row_done2, scores=new_scores, toks=toks,
            fin_scores=fin_scores, fin_toks=fin_toks, caches=caches,
        )

    def _insert(self, state, stat, slot, enc_row, mask_row, limit):
        """Seat one request in ``slot``: prefill output in, KV block zeroed,
        per-slot decode state reset. All `dynamic_update_slice`/scatter —
        the running batch's other slots are never touched."""
        K, T = self.K, self.T
        r0 = slot * K
        enc_out = jax.lax.dynamic_update_slice(
            stat["enc_out"],
            jnp.broadcast_to(
                enc_row[None], (K,) + enc_row.shape
            ).astype(stat["enc_out"].dtype),
            (r0, 0, 0),
        )
        enc_mask = jax.lax.dynamic_update_slice(
            stat["enc_mask"],
            jnp.broadcast_to(
                mask_row[None], (K,) + mask_row.shape
            ).astype(jnp.int32),
            (r0, 0),
        )
        new_stat = dict(
            stat, enc_out=enc_out, enc_mask=enc_mask,
            limit=stat["limit"].at[slot].set(limit),
        )

        def zero_rows(c):
            z = jnp.zeros((K,) + c.shape[1:], dtype=c.dtype)
            return jax.lax.dynamic_update_slice(
                c, z, (r0,) + (0,) * (c.ndim - 1)
            )

        if self.paged:
            # No cache zeroing: position j is written (with real K/V) at
            # step j, before the first step that unmasks it — stale block
            # content is never attended. The block table itself is host
            # state, pushed separately by the seat/release bookkeeping.
            caches = state["caches"]
        else:
            caches = jax.tree_util.tree_map(zero_rows, state["caches"])
        tok = jax.lax.dynamic_update_slice(
            state["tok"],
            jnp.full((K,), self.start_id, dtype=jnp.int32),
            (r0,),
        )
        out = dict(state, caches=caches, tok=tok)
        out["pos"] = state["pos"].at[slot].set(0)
        out["row_done"] = state["row_done"].at[slot].set(False)
        if self.beam:
            out["scores"] = state["scores"].at[slot].set(
                jnp.array(
                    [0.0] + [NEG_INF] * (K - 1), dtype=jnp.float32
                )
            )
            out["toks"] = state["toks"].at[slot].set(
                jnp.full((K, T), self.pad_id, dtype=jnp.int32)
            )
            out["fin_scores"] = state["fin_scores"].at[slot].set(
                jnp.full((K,), -jnp.inf, dtype=jnp.float32)
            )
            out["fin_toks"] = state["fin_toks"].at[slot].set(
                jnp.full((K, T), self.pad_id, dtype=jnp.int32)
            )
        else:
            out["toks"] = state["toks"].at[slot].set(
                jnp.full((T,), self.pad_id, dtype=jnp.int32)
            )
        return out, new_stat

    # ---- host loop ----

    @property
    def occupancy(self) -> int:
        """Requests currently seated in the running batch."""
        return len(self._live)

    @property
    def backlog(self) -> int:
        return len(self._backlog)

    def has_work(self) -> bool:
        return bool(self._live or self._backlog)

    def mean_occupancy(self) -> float:
        if not self.steps_run:
            return 0.0
        return self.occupancy_sum / self.steps_run

    # ---- paged-KV host allocator (ISSUE 16) ----

    @property
    def kv_blocks_total(self) -> int:
        """Usable KV pool blocks (trash block excluded); 0 when dense."""
        return (self.kv_pool_blocks - 1) if self.paged else 0

    @property
    def kv_blocks_free(self) -> int:
        return len(self._free_blocks) if self.paged else 0

    def _blocks_needed(self, limit: int) -> int:
        """Seat-time reservation: the request's WORST CASE, every beam row
        filled to ``limit`` — a seated request can never stall mid-decode."""
        return self.K * (-(-limit // self.kv_block_size))

    def _allocate_blocks(self, slot: int, limit: int) -> None:
        per_row = -(-limit // self.kv_block_size)
        ids: List[int] = []
        for i in range(self.K):
            r = slot * self.K + i
            row_ids = [self._free_blocks.pop() for _ in range(per_row)]
            self._table_np[r, :] = 0
            self._table_np[r, :per_row] = row_ids
            ids.extend(row_ids)
        self._slot_blocks[slot] = ids
        self._table_dirty = True

    def _release_blocks(self, slot: int) -> None:
        ids = self._slot_blocks.pop(slot, None)
        if ids is None:
            return
        self._free_blocks.extend(ids)
        # Repoint the freed rows to the trash block BEFORE their blocks can
        # be reallocated: the freed slot's rows stay frozen in the batch and
        # keep rewriting K/V at their final position every step.
        self._table_np[slot * self.K:(slot + 1) * self.K, :] = 0
        self._table_dirty = True

    def _push_table(self) -> None:
        if self.paged and self._table_dirty:
            self._dyn["caches"]["table"] = jnp.asarray(self._table_np)
            self._table_dirty = False

    def admit(
        self, enc_row, mask_row, limit: int, data: Any = None
    ) -> DecodeTicket:
        """Queue one request (prefill output + per-request token budget).
        Joins the running batch immediately if a slot is free, else waits in
        the backlog and joins between steps as slots free up. Paged mode
        raises :class:`KVPoolExhausted` for a request whose worst-case block
        reservation exceeds the whole pool — it could never be seated."""
        limit = max(1, min(int(limit), self.T))
        if self.paged and self._blocks_needed(limit) > self.kv_blocks_total:
            raise KVPoolExhausted(
                f"request needs {self._blocks_needed(limit)} KV blocks "
                f"(limit={limit} × {self.K} beams, block_size="
                f"{self.kv_block_size}), pool has {self.kv_blocks_total}"
            )
        ticket = DecodeTicket(enc_row, mask_row, limit, data=data)
        ticket.admitted_wall = self._clock()
        ticket.events.append(("admit", ticket.admitted_wall))
        self._backlog.append(ticket)
        self._fill_slots()
        return ticket

    def _fill_slots(self) -> None:
        while self._free and self._backlog:
            if self.paged and (
                self._blocks_needed(self._backlog[0].limit)
                > len(self._free_blocks)
            ):
                # Head-of-line wait: FIFO admission order is part of the
                # bit-identity contract (a later short request must not
                # overtake), so the queue waits for releases, not for a
                # smaller request. Stamp the KV-wait start once (ISSUE 17)
                # — the wait ends when the head finally seats below.
                head = self._backlog[0]
                if head.kv_wait_start is None:
                    head.kv_wait_start = self._clock()
                    head.events.append(("kv_wait", head.kv_wait_start))
                break
            ticket = self._backlog.pop(0)
            slot = self._free.pop(0)
            if self.paged:
                self._allocate_blocks(slot, ticket.limit)
            self._dyn, self._stat = self._jinsert(
                self._dyn, self._stat, np.int32(slot),
                jnp.asarray(ticket.enc_row), jnp.asarray(ticket.mask_row),
                np.int32(ticket.limit),
            )
            ticket.slot = slot
            ticket.joined_wall = self._clock()
            if ticket.kv_wait_start is not None:
                ticket.kv_wait_s = max(
                    0.0, ticket.joined_wall - ticket.kv_wait_start
                )
            ticket.join_step = self.steps_run
            ticket.enc_row = ticket.mask_row = None  # joined: free the host copy
            self._live[slot] = ticket
            # Occupancy the moment this request was seated (itself
            # included) — the "how crowded was the batch I joined" signal.
            ticket.occupancy_at_join = len(self._live)
            ticket.events.append(("seat", ticket.joined_wall))

    def _extract(self, slot: int) -> Tuple[np.ndarray, int]:
        if self.beam:
            out = np.asarray(self._dyn["fin_toks"][slot, 0])
        else:
            out = np.asarray(self._dyn["toks"][slot])
        length = int(
            ((out != self.pad_id) & (out != self.eos_id)).sum()
        )
        return out, length

    def step(self) -> List[DecodeTicket]:
        """One decode iteration of the running batch. Returns the tickets
        that finished this step (their slots are already reseated from the
        backlog — the join happens between steps, never inside one)."""
        if not self._live:
            self._fill_slots()
            if not self._live:
                return []
        self._push_table()
        self._dyn = self._jstep(self._dyn, self._stat)
        self.steps_run += self.micro_steps
        self.occupancy_sum += len(self._live) * self.micro_steps
        self.max_occupancy = max(self.max_occupancy, len(self._live))
        pos = np.asarray(self._dyn["pos"])
        done = np.asarray(self._dyn["row_done"])
        now = self._clock()
        finished: List[DecodeTicket] = []
        for slot, ticket in list(self._live.items()):
            if ticket.first_token_wall is None and pos[slot] >= 1:
                ticket.first_token_wall = now
                ticket.events.append(("first_token", now))
            if done[slot]:
                ticket.steps = int(pos[slot])
                ticket.tokens, ticket.length = self._extract(slot)
                ticket.done_wall = now
                ticket.events.append(("exit", now))
                self.tokens_emitted += max(ticket.steps, ticket.length)
                del self._live[slot]
                self._free.append(slot)
                if self.paged:
                    self._release_blocks(slot)
                finished.append(ticket)
        if finished:
            self._fill_slots()
        return finished

    def run(self, tickets: List[DecodeTicket]) -> None:
        """Pump until every ticket in ``tickets`` finished — the monolithic
        (non-pipelined) path; the pipelined serving loop interleaves
        :meth:`step` with admissions instead."""
        pending = {id(t) for t in tickets if t.done_wall is None}
        while pending:
            for t in self.step():
                pending.discard(id(t))
            if not self.has_work() and pending:
                raise RuntimeError(
                    "continuous engine drained with tickets outstanding"
                )
