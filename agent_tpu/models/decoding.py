"""Shared autoregressive decode engines: greedy and beam scans.

One ``lax.scan`` program per decode (static step count, no per-step retrace,
KV caches threaded through the carry) — the pattern SURVEY.md §7 calls the
hard part of decode-under-jit. The model supplies a step function and its
caches; the engine supplies the control flow, EOS bookkeeping, and (for
beam) the joint top-K + cache reordering. Both the in-house seq2seq family
and the imported BART family run on these engines, so generation semantics
can never drift between families.

``step_fn(tok [B], step scalar, caches) -> (logits [B, V] f32, caches)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from agent_tpu.models.layers import NEG_INF

StepFn = Callable[[jax.Array, jax.Array, Any], Tuple[jax.Array, Any]]



def _ban_eos_before(scores, step, min_length: int, eos_id: int):
    """HF ``MinLengthLogitsProcessor``: EOS masked to ``NEG_INF`` while the
    decoder sequence (start token + generated, HF's counting = step+1) is
    below ``min_length``. Single-sourced so greedy and beam can never drift.
    ``scores``: [..., V] logits or logprobs."""
    if min_length <= 0:
        return scores
    v = scores.shape[-1]
    lead = (1,) * (scores.ndim - 1)
    return jnp.where(
        (step + 1 < min_length)
        & (jnp.arange(v) == eos_id).reshape(lead + (v,)),
        NEG_INF, scores,
    )


def greedy_scan(
    step_fn: StepFn,
    caches: Any,
    batch: int,
    max_new_tokens: int,
    *,
    start_id: int,
    eos_id: int,
    pad_id: int = 0,
    min_length: int = 0,
    forced_first_id: Optional[int] = None,
    forced_last_id: Optional[int] = None,
    early_exit: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy decode → (tokens [B, T], lengths [B]).

    Rows emit ``pad_id`` after their EOS; ``forced_first_id`` (e.g. BART's
    ``forced_bos_token_id``) overrides the step-0 argmax, and
    ``forced_last_id`` (``forced_eos_token_id``) the final step's, when set.
    ``min_length`` bans EOS while the sequence (decoder start + generated,
    HF's counting) is shorter — HF ``MinLengthLogitsProcessor``; a forced
    last token still wins, matching HF's processor order.

    ``early_exit=True`` (default) runs the decode as a ``lax.while_loop``
    that stops once EVERY row has emitted EOS — identical outputs (the
    untouched tail of the token buffer is already ``pad_id``, exactly what
    the full-length scan would write), but a batch of short summaries pays
    for its longest row, not for ``max_new_tokens``. ``False`` keeps the
    fixed-trip ``lax.scan`` (marginally better for batches that always run
    full length, and the differentiable choice if a scoring path ever
    backprops through decode — ``while_loop`` has no reverse rule).
    """
    bos = jnp.full((batch,), start_id, dtype=jnp.int32)
    done0 = jnp.zeros((batch,), dtype=jnp.bool_)
    last = max_new_tokens - 1

    def step_tok(tok, done, caches, step):
        logits, caches = step_fn(tok, step, caches)
        logits = _ban_eos_before(logits, step, min_length, eos_id)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if forced_first_id is not None:
            nxt = jnp.where(step == 0, jnp.int32(forced_first_id), nxt)
        if forced_last_id is not None:
            nxt = jnp.where(step == last, jnp.int32(forced_last_id), nxt)
        nxt = jnp.where(done, jnp.full_like(nxt, pad_id), nxt)
        return nxt, done | (nxt == eos_id), caches

    if early_exit:
        toks0 = jnp.full((batch, max_new_tokens), pad_id, dtype=jnp.int32)

        def cond(carry):
            step, _, done, _, _ = carry
            return jnp.logical_and(step < max_new_tokens, ~jnp.all(done))

        def body(carry):
            step, tok, done, toks, caches = carry
            nxt, done, caches = step_tok(tok, done, caches, step)
            toks = toks.at[:, step].set(nxt)
            return step + 1, nxt, done, toks, caches

        _, _, _, toks, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), bos, done0, toks0, caches)
        )
    else:
        def body(carry, step):
            tok, done, caches = carry
            nxt, done, caches = step_tok(tok, done, caches, step)
            return (nxt, done, caches), nxt

        (_, _, _), toks = jax.lax.scan(
            body, (bos, done0, caches),
            jnp.arange(max_new_tokens, dtype=jnp.int32),
        )
        toks = toks.T  # [B, T]
    lengths = jnp.sum((toks != pad_id) & (toks != eos_id), axis=1)
    return toks, lengths


def beam_scan(
    step_fn: StepFn,
    caches: Any,
    batch: int,
    vocab_size: int,
    max_new_tokens: int,
    *,
    num_beams: int,
    start_id: int,
    eos_id: int,
    pad_id: int = 0,
    length_penalty: float = 1.0,
    early_stopping: bool = False,
    min_length: int = 0,
    forced_first_id: Optional[int] = None,
    forced_last_id: Optional[int] = None,
    cache_reorder: str = "delta",
) -> Tuple[jax.Array, jax.Array]:
    """Beam-search decode → (tokens [B, T], lengths [B]); static shapes.

    HF ``BeamSearchScorer`` semantics, differential-tested token-exact
    against ``transformers`` beam generation (tests/test_bart.py; the
    engine-level invariants — beam1 == greedy, determinism, score
    dominance — live in tests/test_map_summarize.py): each step takes the
    top-2K candidates of
    the joint ``[B, K·V]`` scores; EOS candidates ranked < K bank their
    hypothesis into a static K-slot finished store (normalized by HF's
    length convention — sequence length INCLUDING the decoder start, i.e.
    ``(step+1) ** length_penalty``); the K best non-EOS candidates continue
    (gathering the KV caches along the beam axis). A row stops improving
    once its store holds K hypotheses and — with ``early_stopping=False``,
    the HF default — the best running candidate can no longer beat the
    worst banked one; ``early_stopping=True`` stops at K banked outright.
    After the scan, still-running beams of unfinished rows are banked at
    full length, and each row emits its best hypothesis.

    Beams flatten into the batch dim, so the model's step executable is
    shared with greedy at ``B*K`` rows. ``num_beams=1`` degenerates to
    greedy-with-banking: same emitted tokens as ``greedy_scan``.

    ``cache_reorder`` picks the KV-cache beam-reorder scheme, bit-identical
    outputs either way (regression-tested):

    - ``"delta"`` (default): the per-step gather of every KV cache along the
      beam axis runs under ``lax.cond``, skipped entirely on steps where the
      selected continuation is the identity permutation (each beam extends
      its own parent — ``beam_idx == arange(K)`` for every row, the common
      case once beam frontiers stabilize and for frozen rows). The gather
      moves the FULL [B·K, H, T, D] cache per layer; skipping identity steps
      removes that HBM round trip from most of a long decode.
    - ``"gather"``: the unconditional per-step gather (the pre-delta
      behavior), kept as the equivalence-test reference.
    """
    if cache_reorder not in ("delta", "gather"):
        raise ValueError(
            f"cache_reorder must be 'delta' or 'gather', got {cache_reorder!r}"
        )
    B, K, V, T = batch, num_beams, vocab_size, max_new_tokens
    K2 = 2 * K
    tok0 = jnp.full((B * K,), start_id, dtype=jnp.int32)
    # Step 0: all K beams are identical, so only beam 0 may survive top-K.
    scores0 = jnp.tile(
        jnp.array([0.0] + [NEG_INF] * (K - 1), dtype=jnp.float32), (B, 1)
    )
    toks0 = jnp.full((B, K, T), pad_id, dtype=jnp.int32)
    # Empty finished slots are -inf, NOT the finite NEG_INF: with a negative
    # length_penalty a real hypothesis can normalize below -1e9, and an
    # empty all-pad slot must never outrank a real hypothesis.
    _EMPTY = jnp.float32(-jnp.inf)
    fin_scores0 = jnp.full((B, K), _EMPTY, dtype=jnp.float32)  # normalized
    fin_toks0 = jnp.full((B, K, T), pad_id, dtype=jnp.int32)
    row_done0 = jnp.zeros((B,), dtype=jnp.bool_)
    forced_only = (
        jnp.full((V,), NEG_INF, dtype=jnp.float32).at[forced_first_id].set(0.0)
        if forced_first_id is not None
        else None
    )
    forced_last = (
        jnp.full((V,), NEG_INF, dtype=jnp.float32).at[forced_last_id].set(0.0)
        if forced_last_id is not None
        else None
    )
    lp = jnp.float32(length_penalty)

    def bank(fin_scores, fin_toks, cand_norm, cand_toks):
        """Merge candidate hypotheses into the K-slot finished store.
        cand_norm [B, n] (``_EMPTY`` = ineligible — it must be -inf, see
        the initializer note), cand_toks [B, n, T]."""
        all_scores = jnp.concatenate([fin_scores, cand_norm], axis=1)
        all_toks = jnp.concatenate([fin_toks, cand_toks], axis=1)
        new_scores, sel = jax.lax.top_k(all_scores, K)          # [B, K]
        new_toks = jnp.take_along_axis(all_toks, sel[:, :, None], axis=1)
        return new_scores, new_toks

    def body(carry, step):
        tok, scores, toks, fin_scores, fin_toks, row_done, caches = carry
        logits, caches = step_fn(tok, step, caches)   # [B*K, V]
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, V)
        # Applied BEFORE the forced substitutions, which replace the whole
        # distribution — HF's processor order, so a forced EOS wins.
        logp = _ban_eos_before(logp, step, min_length, eos_id)
        if forced_only is not None:
            logp = jnp.where(step == 0, forced_only[None, None, :], logp)
        if forced_last is not None:
            logp = jnp.where(step == T - 1, forced_last[None, None, :], logp)
        flat = (scores[:, :, None] + logp).reshape(B, K * V)
        cand_scores, idx = jax.lax.top_k(flat, K2)    # [B, 2K]
        cand_beam = idx // V                          # [B, 2K] parent beam
        cand_tok = (idx % V).astype(jnp.int32)
        is_eos = cand_tok == eos_id

        # --- bank EOS candidates (HF: only ranks < K are eligible, and
        # only while the row is still open). Hypothesis length follows
        # HF's convention: decoder start + step generated tokens, the EOS
        # itself excluded from the count → (step + 1).
        hyp_len = (step + 1).astype(jnp.float32)
        eligible = is_eos & (jnp.arange(K2)[None, :] < K) & ~row_done[:, None]
        cand_norm = jnp.where(
            eligible, cand_scores / hyp_len ** lp, _EMPTY
        )
        # Candidate token buffers: parent prefix + EOS written at `step`.
        par_toks = jnp.take_along_axis(toks, cand_beam[:, :, None], axis=1)
        eos_col = jnp.full((B, K2, 1), eos_id, dtype=jnp.int32)
        cand_toks = jax.lax.dynamic_update_slice(par_toks, eos_col,
                                                 (0, 0, step))
        fin_scores, fin_toks = bank(fin_scores, fin_toks, cand_norm,
                                    cand_toks)

        # --- continue with the K best non-EOS candidates. cand_scores are
        # already sorted descending and top_k tie-breaks by index, so this
        # masked top_k returns the first K non-EOS columns in score order.
        # EOS appears at most once per parent beam → at most K of the 2K
        # candidates are EOS → K non-EOS always exist, except at a
        # forced-last step (all mass on EOS) where the selection is
        # irrelevant: the scan ends and every row's store just filled.
        _, gather_pos = jax.lax.top_k(
            jnp.where(is_eos, -jnp.inf, cand_scores), K
        )
        new_scores = jnp.take_along_axis(cand_scores, gather_pos, axis=1)
        new_tok = jnp.take_along_axis(cand_tok, gather_pos, axis=1)
        beam_idx = jnp.take_along_axis(cand_beam, gather_pos, axis=1)

        # Rows already done freeze: emit pad, scores frozen, and the beams
        # keep THEIR OWN slots (identity, not collapse-to-beam-0): a done
        # row's running beams never reach the output (their final-bank
        # normalization is _EMPTY), so any permutation is output-equivalent
        # — identity is the one that lets the delta reorder below skip the
        # cache gather for frozen rows.
        arange_k = jnp.arange(K, dtype=jnp.int32)[None, :]
        new_scores = jnp.where(row_done[:, None], scores, new_scores)
        new_tok = jnp.where(row_done[:, None], pad_id, new_tok)
        beam_idx = jnp.where(row_done[:, None], arange_k, beam_idx)

        toks = jnp.take_along_axis(toks, beam_idx[:, :, None], axis=1)
        toks = jax.lax.dynamic_update_slice(
            toks, new_tok[:, :, None], (0, 0, step)
        )  # frozen rows write pad over pad — a no-op by construction

        # --- HF is_done: store full AND (early_stopping, or the best
        # RUNNING beam — EOS candidates excluded, HF's
        # `_check_early_stop_heuristic` uses the post-selection running
        # scores — can no longer beat the banked worst under the
        # current-length normalization).
        full = jnp.isfinite(fin_scores[:, K - 1])
        if early_stopping:
            newly_done = full
        else:
            best_running = new_scores[:, 0] / hyp_len ** lp
            newly_done = full & (best_running <= fin_scores[:, K - 1])
        row_done = row_done | newly_done

        def reorder(c):
            x = c.reshape(B, K, *c.shape[1:])
            ix = beam_idx.reshape(B, K, *([1] * (c.ndim - 1)))
            return jnp.take_along_axis(x, ix, axis=1).reshape(c.shape)

        def reorder_all(cs):
            return jax.tree_util.tree_map(reorder, cs)

        if cache_reorder == "gather":
            caches = reorder_all(caches)
        else:
            # Delta reorder: gather only when some beam actually switches
            # parent. The identity branch is a pass-through lax.cond arm —
            # no [B·K, H, T, D] gather, no HBM round trip — and shapes stay
            # scan-stable because both arms return the same pytree.
            caches = jax.lax.cond(
                jnp.all(beam_idx == arange_k),
                lambda cs: cs, reorder_all, caches,
            )
        return (
            new_tok.reshape(B * K), new_scores, toks,
            fin_scores, fin_toks, row_done, caches,
        ), None

    # while_loop, not scan: once every row is done further steps are pure
    # frozen no-ops, so a batch of short summaries pays for its longest
    # row, not for max_new_tokens — the same early exit greedy_scan makes.
    # (Nothing backprops through beam decode, so the missing reverse rule
    # costs nothing.)
    def cond(carry):
        return jnp.logical_and(carry[0] < T, ~jnp.all(carry[6]))

    def wbody(carry):
        step = carry[0]
        new_carry, _ = body(carry[1:], step)
        return (step + 1,) + new_carry

    (_, _, scores, toks, fin_scores, fin_toks, row_done, _) = (
        jax.lax.while_loop(
            cond, wbody,
            (jnp.int32(0), tok0, scores0, toks0,
             fin_scores0, fin_toks0, row_done0, caches),
        )
    )

    # Finalize (HF): rows that never closed bank their running beams,
    # normalized by their GENERATED length T — HF's unified rule is
    # "normalize by the hypothesis's generated token count" (an in-scan
    # banked hypothesis has step generated tokens + its EOS = step+1;
    # a run-to-the-end beam has exactly T).
    run_norm = jnp.where(
        row_done[:, None], _EMPTY,
        scores / jnp.float32(T) ** lp,
    )
    fin_scores, fin_toks = bank(fin_scores, fin_toks, run_norm, toks)

    out = fin_toks[:, 0]                                        # [B, T]
    out_len = jnp.sum((out != pad_id) & (out != eos_id), axis=1)
    return out, out_len
