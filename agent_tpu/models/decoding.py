"""Shared autoregressive decode engines: greedy and beam scans.

One ``lax.scan`` program per decode (static step count, no per-step retrace,
KV caches threaded through the carry) — the pattern SURVEY.md §7 calls the
hard part of decode-under-jit. The model supplies a step function and its
caches; the engine supplies the control flow, EOS bookkeeping, and (for
beam) the joint top-K + cache reordering. Both the in-house seq2seq family
and the imported BART family run on these engines, so generation semantics
can never drift between families.

``step_fn(tok [B], step scalar, caches) -> (logits [B, V] f32, caches)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from agent_tpu.models.layers import NEG_INF

StepFn = Callable[[jax.Array, jax.Array, Any], Tuple[jax.Array, Any]]


def greedy_scan(
    step_fn: StepFn,
    caches: Any,
    batch: int,
    max_new_tokens: int,
    *,
    start_id: int,
    eos_id: int,
    pad_id: int = 0,
    forced_first_id: Optional[int] = None,
    forced_last_id: Optional[int] = None,
    early_exit: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy decode → (tokens [B, T], lengths [B]).

    Rows emit ``pad_id`` after their EOS; ``forced_first_id`` (e.g. BART's
    ``forced_bos_token_id``) overrides the step-0 argmax, and
    ``forced_last_id`` (``forced_eos_token_id``) the final step's, when set.

    ``early_exit=True`` (default) runs the decode as a ``lax.while_loop``
    that stops once EVERY row has emitted EOS — identical outputs (the
    untouched tail of the token buffer is already ``pad_id``, exactly what
    the full-length scan would write), but a batch of short summaries pays
    for its longest row, not for ``max_new_tokens``. ``False`` keeps the
    fixed-trip ``lax.scan`` (marginally better for batches that always run
    full length, and the differentiable choice if a scoring path ever
    backprops through decode — ``while_loop`` has no reverse rule).
    """
    bos = jnp.full((batch,), start_id, dtype=jnp.int32)
    done0 = jnp.zeros((batch,), dtype=jnp.bool_)
    last = max_new_tokens - 1

    def step_tok(tok, done, caches, step):
        logits, caches = step_fn(tok, step, caches)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if forced_first_id is not None:
            nxt = jnp.where(step == 0, jnp.int32(forced_first_id), nxt)
        if forced_last_id is not None:
            nxt = jnp.where(step == last, jnp.int32(forced_last_id), nxt)
        nxt = jnp.where(done, jnp.full_like(nxt, pad_id), nxt)
        return nxt, done | (nxt == eos_id), caches

    if early_exit:
        toks0 = jnp.full((batch, max_new_tokens), pad_id, dtype=jnp.int32)

        def cond(carry):
            step, _, done, _, _ = carry
            return jnp.logical_and(step < max_new_tokens, ~jnp.all(done))

        def body(carry):
            step, tok, done, toks, caches = carry
            nxt, done, caches = step_tok(tok, done, caches, step)
            toks = toks.at[:, step].set(nxt)
            return step + 1, nxt, done, toks, caches

        _, _, _, toks, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), bos, done0, toks0, caches)
        )
    else:
        def body(carry, step):
            tok, done, caches = carry
            nxt, done, caches = step_tok(tok, done, caches, step)
            return (nxt, done, caches), nxt

        (_, _, _), toks = jax.lax.scan(
            body, (bos, done0, caches),
            jnp.arange(max_new_tokens, dtype=jnp.int32),
        )
        toks = toks.T  # [B, T]
    lengths = jnp.sum((toks != pad_id) & (toks != eos_id), axis=1)
    return toks, lengths


def beam_scan(
    step_fn: StepFn,
    caches: Any,
    batch: int,
    vocab_size: int,
    max_new_tokens: int,
    *,
    num_beams: int,
    start_id: int,
    eos_id: int,
    pad_id: int = 0,
    length_penalty: float = 1.0,
    forced_first_id: Optional[int] = None,
    forced_last_id: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Beam-search decode → (tokens [B, T], lengths [B]); static shapes.

    Beams flatten into the batch dim (the model's step executable is shared
    with greedy at ``B*K`` rows); each step takes one top-K over the joint
    ``[B, K*V]`` scores and gathers the KV caches along the beam axis.
    Finished beams collapse their next-token distribution to ``pad_id`` at
    zero cost, freezing their score. Selection normalizes by
    ``length ** length_penalty``. ``num_beams=1`` reduces to exactly greedy.
    """
    B, K, V, T = batch, num_beams, vocab_size, max_new_tokens
    tok0 = jnp.full((B * K,), start_id, dtype=jnp.int32)
    # Step 0: all K beams are identical, so only beam 0 may survive top-K.
    scores0 = jnp.tile(
        jnp.array([0.0] + [NEG_INF] * (K - 1), dtype=jnp.float32), (B, 1)
    )
    done0 = jnp.zeros((B, K), dtype=jnp.bool_)
    toks0 = jnp.zeros((B, K, T), dtype=jnp.int32)
    pad_only = jnp.full((V,), NEG_INF, dtype=jnp.float32).at[pad_id].set(0.0)
    forced_only = (
        jnp.full((V,), NEG_INF, dtype=jnp.float32).at[forced_first_id].set(0.0)
        if forced_first_id is not None
        else None
    )
    forced_last = (
        jnp.full((V,), NEG_INF, dtype=jnp.float32).at[forced_last_id].set(0.0)
        if forced_last_id is not None
        else None
    )

    def body(carry, step):
        tok, scores, done, toks, caches = carry
        logits, caches = step_fn(tok, step, caches)   # [B*K, V]
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, V)
        if forced_only is not None:
            logp = jnp.where(step == 0, forced_only[None, None, :], logp)
        if forced_last is not None:
            logp = jnp.where(step == T - 1, forced_last[None, None, :], logp)
        logp = jnp.where(done[:, :, None], pad_only[None, None, :], logp)
        flat = (scores[:, :, None] + logp).reshape(B, K * V)
        new_scores, idx = jax.lax.top_k(flat, K)      # [B, K]
        beam_idx = idx // V                           # [B, K] parent beam
        new_tok = (idx % V).astype(jnp.int32)

        toks = jnp.take_along_axis(toks, beam_idx[:, :, None], axis=1)
        toks = jax.lax.dynamic_update_slice(
            toks, new_tok[:, :, None], (0, 0, step)
        )
        done = jnp.take_along_axis(done, beam_idx, axis=1) | (new_tok == eos_id)

        def reorder(c):
            x = c.reshape(B, K, *c.shape[1:])
            ix = beam_idx.reshape(B, K, *([1] * (c.ndim - 1)))
            return jnp.take_along_axis(x, ix, axis=1).reshape(c.shape)

        caches = jax.tree_util.tree_map(reorder, caches)
        return (new_tok.reshape(B * K), new_scores, done, toks, caches), None

    (_, scores, _, toks, _), _ = jax.lax.scan(
        body, (tok0, scores0, done0, toks0, caches),
        jnp.arange(T, dtype=jnp.int32),
    )
    lengths = jnp.sum((toks != pad_id) & (toks != eos_id), axis=2)  # [B, K]
    norm = scores / jnp.maximum(lengths, 1).astype(jnp.float32) ** length_penalty
    best = jnp.argmax(norm, axis=1)
    out = jnp.take_along_axis(toks, best[:, None, None], axis=1)[:, 0]
    out_len = jnp.take_along_axis(lengths, best[:, None], axis=1)[:, 0]
    return out, out_len
