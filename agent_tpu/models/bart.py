"""HF-BART-compatible seq2seq: serve *pretrained* summarization checkpoints.

The reference's summarize op ran a hub BART checkpoint through host torch
(reference ``ops/map_summarize.py:29-32,52-59``). This module serves the same
checkpoints TPU-side: ``model_path`` → a local HF BART directory
(``config.json`` + weights + ``vocab.json``/``merges.txt``) → faithful
post-LN encoder-decoder forward (learned offset-2 positions, embedding
LayerNorm, tied lm_head + ``final_logits_bias``), with generation under the
shared one-program scan engines (``models/decoding.py``) — KV-cached greedy
or beam decode, honoring the checkpoint's ``decoder_start_token_id`` /
``forced_bos_token_id``. Differential-tested against ``transformers``'
reference implementation (logits and generated tokens).

No network access anywhere: checkpoints load from local disk only.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from agent_tpu.models import layers
from agent_tpu.models.layers import Params, dot_product_attention


@dataclass(frozen=True)
class BartConfig:
    """Mirror of the HF BART ``config.json`` fields the forward needs."""

    vocab_size: int = 50265
    d_model: int = 768
    n_heads: int = 12
    n_enc_layers: int = 6
    n_dec_layers: int = 6
    d_ff: int = 3072
    max_position: int = 1024
    pad_id: int = 1
    bos_id: int = 0
    eos_id: int = 2
    decoder_start_id: int = 2
    forced_bos_id: Optional[int] = None
    forced_eos_id: Optional[int] = 2  # HF BART forces EOS at max length
    scale_embedding: bool = False
    dtype: str = "bfloat16"
    # "int8": serve with W8A8 quantized matmuls (models.quant); "w8a16":
    # weight-only int8 — the decode-mode recipe (int8-resident weights
    # dequantized in-register, activations stay at dtype).
    quant: str = "none"

    # Uniform serving-config view (map_summarize reads these off any family).
    @property
    def max_src_len(self) -> int:
        return self.max_position

    @property
    def max_tgt_len(self) -> int:
        return self.max_position

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def from_hf_json(cls, path: str, **overrides) -> "BartConfig":
        try:
            with open(path) as f:
                hf = json.load(f)
        except json.JSONDecodeError as exc:
            # NOT a ValueError: JSONDecodeError subclasses it and would be
            # soft-dropped as caller bad_input; a corrupt checkpoint is a
            # retryable integrity failure (same contract as models/bert.py).
            raise RuntimeError(
                f"unreadable checkpoint config.json at {path}: {exc}"
            ) from exc
        if hf.get("model_type") not in (None, "bart"):
            raise RuntimeError(
                f"not a BART checkpoint (model_type={hf.get('model_type')!r})"
            )
        # Newer transformers saves generation controls to a sibling
        # generation_config.json; overlay the ones generation honors here.
        gen_path = os.path.join(os.path.dirname(path), "generation_config.json")
        if os.path.exists(gen_path):
            try:
                with open(gen_path) as f:
                    gen = json.load(f)
                for key in (
                    "decoder_start_token_id",
                    "forced_bos_token_id",
                    "forced_eos_token_id",
                ):
                    if gen.get(key) is not None:
                        hf[key] = gen[key]
            except json.JSONDecodeError:
                pass  # optional overlay; config.json remains authoritative
        # _ffn hardcodes exact GELU (the bart-base/large value); a checkpoint
        # with any other activation_function would be silently mis-served, so
        # whitelist and fail loudly (retryable integrity error).
        act = hf.get("activation_function", "gelu")
        if act != "gelu":
            raise RuntimeError(
                f"unsupported BART activation_function={act!r} "
                "(supported: 'gelu')"
            )
        fields = dict(
            vocab_size=hf["vocab_size"],
            d_model=hf["d_model"],
            n_heads=hf["encoder_attention_heads"],
            n_enc_layers=hf["encoder_layers"],
            n_dec_layers=hf["decoder_layers"],
            d_ff=hf["encoder_ffn_dim"],
            max_position=hf["max_position_embeddings"],
            pad_id=hf.get("pad_token_id", 1),
            bos_id=hf.get("bos_token_id", 0),
            eos_id=hf.get("eos_token_id", 2),
            decoder_start_id=hf.get(
                "decoder_start_token_id", hf.get("eos_token_id", 2)
            ),
            forced_bos_id=hf.get("forced_bos_token_id"),
            forced_eos_id=hf.get("forced_eos_token_id", 2),
            scale_embedding=hf.get("scale_embedding", False),
        )
        fields.update(overrides)
        return cls(**fields)


_LN_EPS = 1e-5  # BART's LayerNorm eps


def _ln(p: Params, x: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(axis=-1, keepdims=True)
    out = (x32 - mu) / jnp.sqrt(var + _LN_EPS)
    return (out * p["scale"] + p["bias"]).astype(x.dtype)


def _embed(params: Params, branch: str, ids: jax.Array, pos0, cfg) -> jax.Array:
    """Token + learned-position (offset 2) embeddings, then embedding LN.

    ``pos0`` is the absolute position of ``ids[:, 0]`` (0 for a full
    sequence, the step index during cached decode).
    """
    dtype = cfg.compute_dtype
    p = params[branch]
    scale = float(np.sqrt(cfg.d_model)) if cfg.scale_embedding else 1.0
    L = ids.shape[1]
    # jnp.asarray: host-numpy param leaves must be liftable for indexing by
    # a traced id array / traced slice start (no-op for device arrays).
    x = jnp.asarray(params["embed"]).astype(dtype)[ids] * dtype.type(scale)
    # HF BartLearnedPositionalEmbedding: weight row = position + 2.
    pos = jax.lax.dynamic_slice_in_dim(
        jnp.asarray(p["pos"]).astype(dtype), pos0 + 2, L, axis=0
    )
    return _ln(p["ln_emb"], x + pos[None])


def _mha(blk: Params, q_in, kv_in, mask, cfg, attn_fn) -> jax.Array:
    """One multi-head attention through the injectable ``attn_fn`` contract
    (so flash/ring compose); blk = {q, k, v, o} dense params."""
    dtype = cfg.compute_dtype
    B, Lq, _ = q_in.shape
    Lk = kv_in.shape[1]
    d_head = cfg.d_model // cfg.n_heads

    def heads(t, L):
        return t.reshape(B, L, cfg.n_heads, d_head).transpose(0, 2, 1, 3)

    q = heads(layers.dense(blk["q"], q_in, dtype), Lq)
    k = heads(layers.dense(blk["k"], kv_in, dtype), Lk)
    v = heads(layers.dense(blk["v"], kv_in, dtype), Lk)
    ctx = attn_fn(q, k, v, mask)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, Lq, cfg.d_model)
    return layers.dense(blk["o"], ctx, dtype)


def _ffn(blk: Params, x, cfg) -> jax.Array:
    dtype = cfg.compute_dtype
    h = jax.nn.gelu(
        layers.dense(blk["fc1"], x, dtype).astype(jnp.float32),
        approximate=False,
    ).astype(dtype)
    return layers.dense(blk["fc2"], h, dtype)


def encode(params: Params, src_ids: jax.Array, src_mask: jax.Array,
           cfg: BartConfig, attn_fn=dot_product_attention) -> jax.Array:
    """Encoder stack → [B, Ls, d] (post-LN, HF BartEncoder semantics)."""
    x = _embed(params, "enc", src_ids, 0, cfg)
    attn_mask = layers.pad_mask_to_attn(src_mask)
    for blk in params["enc"]["layers"]:
        x = _ln(blk["ln1"], x + _mha(blk["self"], x, x, attn_mask, cfg, attn_fn))
        x = _ln(blk["ln2"], x + _ffn(blk, x, cfg))
    return x


def _lm_logits(params: Params, x: jax.Array, cfg: BartConfig) -> jax.Array:
    dtype = cfg.compute_dtype
    logits = jnp.dot(x.astype(dtype), params["embed"].astype(dtype).T)
    return (logits.astype(jnp.float32) + params["final_logits_bias"][None])


def decode_full(params: Params, tgt_ids: jax.Array, enc_out: jax.Array,
                enc_mask: jax.Array, cfg: BartConfig,
                attn_fn=dot_product_attention) -> jax.Array:
    """Teacher-forced decoder → lm logits [B, Lt, V] (causal mask). The
    differential-test surface: matches HF ``BartForConditionalGeneration``
    logits given ``decoder_input_ids``."""
    B, Lt = tgt_ids.shape
    x = _embed(params, "dec", tgt_ids, 0, cfg)
    causal = jnp.tril(jnp.ones((Lt, Lt), dtype=jnp.int32))[None, None]
    enc_attn = enc_mask[:, None, None, :]
    for blk in params["dec"]["layers"]:
        x = _ln(blk["ln1"], x + _mha(blk["self"], x, x, causal, cfg, attn_fn))
        x = _ln(blk["ln_x"],
                x + _mha(blk["cross"], x, enc_out, enc_attn, cfg, attn_fn))
        x = _ln(blk["ln2"], x + _ffn(blk, x, cfg))
    return _lm_logits(params, x, cfg)


# ---- cached single-step decode (generation) ----


def _init_self_caches(cfg: BartConfig, batch: int, max_new: int) -> list:
    """Empty static-length self-attention KV caches, one per decoder layer."""
    d_head = cfg.d_model // cfg.n_heads
    dtype = cfg.compute_dtype
    return [
        {
            "k": jnp.zeros((batch, cfg.n_heads, max_new, d_head), dtype=dtype),
            "v": jnp.zeros((batch, cfg.n_heads, max_new, d_head), dtype=dtype),
        }
        for _ in range(cfg.n_dec_layers)
    ]


def _init_cross_kv(params: Params, enc_out: jax.Array, cfg: BartConfig) -> list:
    """Cross-attention K/V computed ONCE from the encoder output. These are
    loop-invariant: the step function closes over them rather than carrying
    them through the scan (a beam search must not gather/reorder [B·K, H,
    Ls, d] tensors that are identical across beams at every step)."""
    B, Ls, _ = enc_out.shape
    d_head = cfg.d_model // cfg.n_heads
    dtype = cfg.compute_dtype

    def heads(t):
        return t.reshape(B, Ls, cfg.n_heads, d_head).transpose(0, 2, 1, 3)

    return [
        {
            "k": heads(layers.dense(blk["cross"]["k"], enc_out, dtype)),
            "v": heads(layers.dense(blk["cross"]["v"], enc_out, dtype)),
        }
        for blk in params["dec"]["layers"]
    ]


def decode_step(params: Params, tok: jax.Array, step: jax.Array,
                self_caches: list, cross_kv: list, enc_mask: jax.Array,
                cfg: BartConfig, max_new: int) -> Tuple[jax.Array, list]:
    """One cached decoder step → (logits [B, V] f32, self_caches)."""
    dtype = cfg.compute_dtype
    B = tok.shape[0]
    d_head = cfg.d_model // cfg.n_heads
    x = _embed(params, "dec", tok[:, None], step, cfg)  # [B, 1, d]
    self_mask = (jnp.arange(max_new) <= step).astype(jnp.int32)[None, None, None]
    enc_attn = enc_mask[:, None, None, :]
    new_self = []
    for blk, s_kv, x_kv in zip(
        params["dec"]["layers"], self_caches, cross_kv
    ):
        a = blk["self"]
        q = layers.dense(a["q"], x, dtype).reshape(B, 1, cfg.n_heads, d_head)
        q = q.transpose(0, 2, 1, 3)
        k1 = layers.dense(a["k"], x, dtype).reshape(B, 1, cfg.n_heads, d_head)
        v1 = layers.dense(a["v"], x, dtype).reshape(B, 1, cfg.n_heads, d_head)
        k = jax.lax.dynamic_update_slice(
            s_kv["k"], k1.transpose(0, 2, 1, 3), (0, 0, step, 0)
        )
        v = jax.lax.dynamic_update_slice(
            s_kv["v"], v1.transpose(0, 2, 1, 3), (0, 0, step, 0)
        )
        new_self.append({"k": k, "v": v})
        ctx = dot_product_attention(q, k, v, self_mask)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, 1, cfg.d_model)
        x = _ln(blk["ln1"], x + layers.dense(a["o"], ctx, dtype))
        # Cross-attention against the precomputed encoder K/V.
        c = blk["cross"]
        qx = layers.dense(c["q"], x, dtype).reshape(B, 1, cfg.n_heads, d_head)
        qx = qx.transpose(0, 2, 1, 3)
        cctx = dot_product_attention(qx, x_kv["k"], x_kv["v"], enc_attn)
        cctx = cctx.transpose(0, 2, 1, 3).reshape(B, 1, cfg.d_model)
        x = _ln(blk["ln_x"], x + layers.dense(c["o"], cctx, dtype))
        x = _ln(blk["ln2"], x + _ffn(blk, x, cfg))
    return _lm_logits(params, x, cfg)[:, 0], new_self


def generate(
    params: Params,
    src_ids: jax.Array,
    src_mask: jax.Array,
    cfg: BartConfig,
    max_new_tokens: int,
    num_beams: int = 1,
    length_penalty: float = 1.0,
    early_stopping: bool = False,
    min_length: int = 0,
    attn_fn=dot_product_attention,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy (or beam) generation under one jit trace via the shared scan
    engines. Returns (tokens [B, T], lengths [B]); tokens after EOS are the
    checkpoint's pad id. ``attn_fn`` applies to the encoder pass (where the
    long context lives)."""
    from agent_tpu.models.decoding import beam_scan, greedy_scan

    B = src_ids.shape[0]
    enc_out = encode(params, src_ids, src_mask, cfg, attn_fn=attn_fn)
    if num_beams <= 1:
        cross_kv = _init_cross_kv(params, enc_out, cfg)

        def step_fn(tok, step, caches):
            return decode_step(
                params, tok, step, caches, cross_kv, src_mask, cfg,
                max_new_tokens,
            )

        return greedy_scan(
            step_fn, _init_self_caches(cfg, B, max_new_tokens), B,
            max_new_tokens,
            start_id=cfg.decoder_start_id, eos_id=cfg.eos_id,
            pad_id=cfg.pad_id, min_length=min_length,
            forced_first_id=cfg.forced_bos_id,
            forced_last_id=cfg.forced_eos_id,
        )
    K = num_beams
    enc_out = jnp.repeat(enc_out, K, axis=0)
    enc_mask = jnp.repeat(src_mask, K, axis=0)
    # Cross K/V repeat with the beams but stay OUT of the scan carry: they
    # are identical across steps (and across a row's beams), so reordering
    # them per step would be pure waste — beam_scan only reorders the
    # self caches.
    cross_kv = _init_cross_kv(params, enc_out, cfg)

    def step_fn(tok, step, caches):
        return decode_step(
            params, tok, step, caches, cross_kv, enc_mask, cfg,
            max_new_tokens,
        )

    return beam_scan(
        step_fn, _init_self_caches(cfg, B * K, max_new_tokens), B,
        cfg.vocab_size, max_new_tokens,
        num_beams=K, length_penalty=length_penalty,
        early_stopping=early_stopping, min_length=min_length,
        start_id=cfg.decoder_start_id, eos_id=cfg.eos_id,
        pad_id=cfg.pad_id, forced_first_id=cfg.forced_bos_id,
        forced_last_id=cfg.forced_eos_id,
    )


# ---- weight import ----


def _dense_from(sd, prefix: str) -> Params:
    return {
        "w": np.ascontiguousarray(sd[f"{prefix}.weight"].T),
        "b": sd[f"{prefix}.bias"],
    }


def _ln_from(sd, prefix: str) -> Params:
    return {"scale": sd[f"{prefix}.weight"], "bias": sd[f"{prefix}.bias"]}


def _attn_from(sd, prefix: str) -> Params:
    return {
        "q": _dense_from(sd, f"{prefix}.q_proj"),
        "k": _dense_from(sd, f"{prefix}.k_proj"),
        "v": _dense_from(sd, f"{prefix}.v_proj"),
        "o": _dense_from(sd, f"{prefix}.out_proj"),
    }


def from_state_dict(sd: Dict[str, np.ndarray], cfg: BartConfig) -> Params:
    """HF BART state dict (``BartModel`` or ``BartForConditionalGeneration``
    naming — the ``model.`` prefix is stripped) → our param pytree."""
    sd = {
        (k[6:] if k.startswith("model.") else k): np.asarray(v)
        for k, v in sd.items()
    }

    def branch(name: str, n_layers: int, cross: bool) -> Params:
        out: Params = {
            "pos": sd[f"{name}.embed_positions.weight"],
            "ln_emb": _ln_from(sd, f"{name}.layernorm_embedding"),
            "layers": [],
        }
        for i in range(n_layers):
            p = f"{name}.layers.{i}"
            blk: Params = {
                "self": _attn_from(sd, f"{p}.self_attn"),
                "ln1": _ln_from(sd, f"{p}.self_attn_layer_norm"),
                "fc1": _dense_from(sd, f"{p}.fc1"),
                "fc2": _dense_from(sd, f"{p}.fc2"),
                "ln2": _ln_from(sd, f"{p}.final_layer_norm"),
            }
            if cross:
                blk["cross"] = _attn_from(sd, f"{p}.encoder_attn")
                blk["ln_x"] = _ln_from(sd, f"{p}.encoder_attn_layer_norm")
            out["layers"].append(blk)
        return out

    bias = sd.get("final_logits_bias")
    if bias is None:
        bias = np.zeros((cfg.vocab_size,), dtype=np.float32)
    return {
        "embed": sd["shared.weight"],
        "final_logits_bias": np.asarray(bias).reshape(-1).astype(np.float32),
        "enc": branch("encoder", cfg.n_enc_layers, cross=False),
        "dec": branch("decoder", cfg.n_dec_layers, cross=True),
    }


def is_hf_bart_dir(path: str) -> bool:
    """A local HF BART checkpoint directory (config.json, model_type bart)."""
    cfg_path = os.path.join(path, "config.json")
    if not os.path.isdir(path) or not os.path.exists(cfg_path):
        return False
    try:
        with open(cfg_path) as f:
            return json.load(f).get("model_type") == "bart"
    except Exception:  # noqa: BLE001 — unreadable json resolves at load time
        return True  # claim it; load_hf_dir surfaces the real error


def load_hf_dir(path: str, **config_overrides) -> Tuple[BartConfig, Params]:
    """Load (config, params) from a local HF BART checkpoint directory —
    ``model.safetensors`` preferred, else ``pytorch_model.bin`` (torch
    imports lazily; CPU map)."""
    cfg = BartConfig.from_hf_json(
        os.path.join(path, "config.json"), **config_overrides
    )
    st_path = os.path.join(path, "model.safetensors")
    bin_path = os.path.join(path, "pytorch_model.bin")
    if os.path.exists(st_path):
        try:
            from safetensors.numpy import load_file

            return cfg, from_state_dict(load_file(st_path), cfg)
        except ImportError:
            pass
    if not os.path.exists(bin_path):
        raise FileNotFoundError(
            f"no model.safetensors or pytorch_model.bin under {path}"
        )
    import torch

    raw = torch.load(bin_path, map_location="cpu", weights_only=True)
    return cfg, from_state_dict({k: v.numpy() for k, v in raw.items()}, cfg)


# ---- tokenizer ----


def hf_bpe(path: str):
    """The checkpoint's byte-level BPE tokenizer (vocab.json + merges.txt);
    ``ByteLevelBPE.from_dir`` caches per directory."""
    from agent_tpu.models.bpe import ByteLevelBPE

    if not os.path.exists(os.path.join(path, "vocab.json")):
        raise ValueError(f"BART checkpoint {path} has no vocab.json")
    return ByteLevelBPE.from_dir(path)


def encode_pad_batch(
    tok, texts, cfg: BartConfig, batch_buckets, length_buckets
) -> Tuple[np.ndarray, np.ndarray]:
    """``<s> pieces </s>`` per row → (ids [B, L] int32, lengths [B] int32)
    with bucketed static shapes; bucket truncation keeps the trailing
    ``</s>`` (transformers truncation semantics)."""
    from agent_tpu.models.tokenizer import bucket_length

    max_len = cfg.max_src_len
    rows: List[List[int]] = [
        [cfg.bos_id] + tok.encode(t)[: max_len - 2] + [cfg.eos_id]
        for t in texts
    ]
    longest = max(len(r) for r in rows)
    L = bucket_length(min(longest, max_len), length_buckets)
    B = bucket_length(len(rows), batch_buckets)
    ids = np.full((B, L), cfg.pad_id, dtype=np.int32)
    lengths = np.zeros(B, dtype=np.int32)
    for r, row in enumerate(rows):
        if len(row) > L:
            row = row[: L - 1] + [cfg.eos_id]
        ids[r, : len(row)] = row
        lengths[r] = len(row)
    return ids, lengths
