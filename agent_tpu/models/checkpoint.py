"""Checkpoint save/restore for model param pytrees (SURVEY.md §5.4).

The reference's "checkpoints" were immutable input artifacts — a compiled
``.tflite`` blob at a well-known path (reference ``_tpu_runtime.py:23-31``)
and HF hub weights (reference ``ops/map_summarize.py:29-30``). The framework
needs the producing side too: training (``models/train.py``) must be able to
emit an artifact the ops load by path (both model ops accept a ``model_path``
ending in ``.npz``).

Two formats:

- **``.npz``** (primary): flat dotted-key arrays, the inverse of
  ``layers.assign_from_npz``. Host-gathered, single-file, dependency-free —
  right for the op-served model sizes here.
- **Orbax** (optional): sharded save/restore for params that live distributed
  over a mesh — each host writes only its shards, nothing is gathered. Used
  when ``orbax-checkpoint`` is importable; guarded so the framework never
  requires it.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Tuple

import numpy as np

import jax

Params = Dict[str, Any]


def flatten_params(params: Params, prefix: str = "") -> List[Tuple[str, Any]]:
    """Pytree → ``[('blocks.0.attn.wq', leaf), ...]`` in deterministic order
    (the key grammar of ``layers.assign_from_npz``)."""
    out: List[Tuple[str, Any]] = []
    if isinstance(params, dict):
        for k in sorted(params):
            out.extend(flatten_params(params[k], f"{prefix}{k}."))
    elif isinstance(params, (list, tuple)):
        for i, v in enumerate(params):
            out.extend(flatten_params(v, f"{prefix}{i}."))
    else:
        out.append((prefix[:-1], params))
    return out


def save_npz(params: Params, path: str) -> str:
    """Write a param pytree to ``path`` as a flat ``.npz``; returns ``path``.

    Device arrays are fetched to host (sharded leaves gather transparently).
    The write is atomic (temp file + rename) so a crash never leaves a
    half-written artifact at a path an op might load.
    """
    flat = {k: np.asarray(v) for k, v in flatten_params(params)}
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return path


def orbax_available() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def save_orbax(params: Params, path: str) -> str:
    """Sharded save: each host writes its own shards (no gather)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params, force=True)
    return path


def load_orbax(path: str, like: Params) -> Params:
    """Restore with ``like``'s structure/shardings (pass a sharded init pytree
    to restore distributed — leaves land where ``like``'s leaves live)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path), target=like)


def params_equal(a: Params, b: Params, atol: float = 0.0) -> bool:
    """Exact (or atol-bounded) leaf-wise equality — checkpoint tests' oracle."""
    fa, fb = flatten_params(a), flatten_params(b)
    if [k for k, _ in fa] != [k for k, _ in fb]:
        return False
    for (_, va), (_, vb) in zip(fa, fb):
        va, vb = np.asarray(va), np.asarray(vb)
        if va.shape != vb.shape:
            return False
        if not np.allclose(va, vb, rtol=0.0, atol=atol):
            return False
    return True
