"""Seeded chaos-testing primitives (ISSUE 3 tentpole 4).

The controller's original fault surface was three *one-shot* ``inject()``
faults — enough to unit-test a single fence, useless for exercising
sustained failure. This module makes failure a first-class, reproducible
input:

- ``FaultPlan`` — a seeded, probabilistic plan over named fault kinds. One
  ``random.Random(seed)`` drives every decision, so the same seed + the same
  call sequence replays the same fault pattern (the property
  ``tests/test_chaos.py`` pins). Every injected fault is counted in
  ``plan.counts`` so a soak can reconcile *injected* against *observed*.
- ``ChaosSession`` — wraps any ``session.post`` with plan-driven transport
  faults on the agent side of the wire: drop the request (never delivered),
  drop the response (delivered, answer lost — the nasty case: the controller
  applied the result but the agent must assume it didn't), fabricate an
  HTTP 500 after delivery, deliver a result twice, or delay. Counted into
  ``chaos_faults_injected_total{fault,path}`` when given a registry.
- ``LoopbackSession`` — an in-process "HTTP" session: ``post`` calls a
  ``Controller`` directly with the same request/response shapes as
  ``controller/server.py``. Lets the chaos soak drive the *real* ``Agent``
  loop against a *real* ``Controller`` deterministically, no sockets.
- ``GatedSession`` — a controller-outage switch: while ``down``, every post
  raises a transport error. The soak uses it to prove a controller outage
  shorter than the lease TTL causes zero shard re-executions (the spool
  redelivers instead).

The controller side (probabilistic ``drop_lease`` / ``duplicate_task`` /
``stale_epoch``) consumes the same plan via ``Controller.inject(plan=...)``.
"""

from __future__ import annotations

import json as _json
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class ChaosTransportError(ConnectionError):
    """The transport-failure exception injected faults raise — a
    ``ConnectionError`` so real retry paths treat it exactly like a dropped
    TCP connection."""


@dataclass
class FaultPlan:
    """A seeded probability per fault kind; 0.0 disables a kind.

    Agent-side kinds (``ChaosSession``): ``drop_request``, ``drop_response``,
    ``http_500``, ``duplicate_result``, ``delay`` (+ ``delay_max_sec``).
    Controller-side kinds (``Controller.inject(plan=...)``): ``drop_lease``,
    ``duplicate_task``, ``stale_epoch``. Harness-level: ``agent_crash``
    (the soak abandons a granted lease and restarts the agent), plus the
    preemption kinds (ISSUE 10): ``spot_reclaim`` — SIGTERM with a grace
    window, the member runs the full drain path (finish/release the
    in-flight lease, flush spool + final metrics, exit clean) before the
    capacity disappears — and ``hard_kill`` — SIGKILL mid-execute, no
    drain: in-flight work is lost and must be recovered by lease-TTL
    expiry + epoch fencing while the autoscaler replaces the capacity.
    ``controller_kill`` (ISSUE 14) SIGKILLs the PRIMARY CONTROLLER itself
    mid-drain; recovery is hot-standby promotion + agent CONTROLLER_URLS
    failover + spool redelivery.
    """

    seed: int = 0
    # agent-side transport faults
    drop_request: float = 0.0
    drop_response: float = 0.0
    http_500: float = 0.0
    duplicate_result: float = 0.0
    delay: float = 0.0
    delay_max_sec: float = 0.0
    # controller-side faults
    drop_lease: float = 0.0
    duplicate_task: float = 0.0
    stale_epoch: float = 0.0
    # harness-level faults
    agent_crash: float = 0.0
    # preemption faults (ISSUE 10): decided per live member per churn tick
    spot_reclaim: float = 0.0
    hard_kill: float = 0.0
    # control-plane fault (ISSUE 14): SIGKILL the PRIMARY CONTROLLER
    # mid-drain — no close(), no journal fsync, a possibly-torn final
    # journal line. Recovery is the hot-standby promotion path
    # (controller/standby.py): journal tail + seal + epoch-fenced requeue,
    # with agents failing over via CONTROLLER_URLS and the spool
    # redelivering completed results to the new incarnation. Decided by
    # the soak harness per tick (scripts/controller_failover_soak.py).
    controller_kill: float = 0.0
    counts: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()

    def decide(self, fault: str) -> bool:
        """One Bernoulli draw for ``fault``; hits are tallied in ``counts``.
        A zero-probability kind consumes no randomness, so enabling one
        fault never perturbs another's sequence."""
        prob = float(getattr(self, fault))
        if prob <= 0.0:
            return False
        with self._lock:
            hit = self._rng.random() < prob
            if hit:
                self.counts[fault] = self.counts.get(fault, 0) + 1
        return hit

    def draw_delay(self) -> float:
        with self._lock:
            return self._rng.uniform(0.0, max(0.0, self.delay_max_sec))

    def total_injected(self) -> int:
        with self._lock:
            return sum(self.counts.values())


class _FakeResponse:
    """The minimal response surface the agent reads."""

    def __init__(self, status_code: int, body: Any = None) -> None:
        self.status_code = status_code
        self._body = body
        self.text = "" if body is None else _json.dumps(body, default=str)

    def json(self) -> Any:
        if self._body is None:
            raise ValueError("no body")
        return self._body


def _path_of(url: str) -> str:
    if url.endswith("/v1/leases"):
        return "leases"
    if url.endswith("/v1/results"):
        return "results"
    if url.endswith("/v1/jobs"):
        return "jobs"
    if url.endswith("/v1/workflows"):
        return "workflows"
    return "other"


class LoopbackSession:
    """In-process stand-in for ``requests.Session`` over a ``Controller`` —
    the same dispatch ``controller/server.py`` does, minus the sockets."""

    def __init__(self, controller: Any) -> None:
        self.controller = controller

    def post(self, url: str, json: Any = None, timeout: Any = None):  # noqa: A002
        body = json or {}
        path = _path_of(url)
        if path == "leases":
            raw_max = body.get("max_tasks")
            out = self.controller.lease(
                agent=str(body.get("agent", "")),
                capabilities=body.get("capabilities"),
                max_tasks=1 if raw_max is None else int(raw_max),
                worker_profile=body.get("worker_profile"),
                metrics=body.get("metrics"),
                labels=body.get("labels")
                if isinstance(body.get("labels"), dict) else None,
                # Drain handshake (ISSUE 10): a retiring agent's final
                # metrics-only poll marks it `draining` in /v1/status.
                draining=bool(body.get("draining")),
            )
            return (
                _FakeResponse(204) if out is None else _FakeResponse(200, out)
            )
        if path == "results":
            out = self.controller.report(
                lease_id=str(body.get("lease_id", "")),
                job_id=str(body.get("job_id", "")),
                job_epoch=body.get("job_epoch"),
                status=str(body.get("status", "")),
                result=body.get("result"),
                error=body.get("error"),
                spans=body.get("spans"),
            )
            return _FakeResponse(200, out)
        if path == "jobs":
            # Single-job submit with the scheduling fields (ISSUE 4) — the
            # same dispatch controller/server.py does, including the 429
            # admission response, so soaks can exercise backpressure
            # in-process.
            from agent_tpu.sched import AdmissionError

            try:
                job_id = self.controller.submit(
                    op=str(body.get("op", "")),
                    payload=body.get("payload"),
                    # Client-chosen id (ISSUE 14): same exactly-once
                    # resubmission contract as controller/server.py.
                    job_id=(
                        str(body["job_id"])
                        if body.get("job_id") is not None else None
                    ),
                    required_labels=body.get("required_labels"),
                    max_attempts=body.get("max_attempts"),
                    priority=body.get("priority"),
                    tenant=body.get("tenant"),
                    deadline_sec=body.get("deadline_sec"),
                )
            except AdmissionError as exc:
                return _FakeResponse(429, {
                    "error": str(exc),
                    "retry_after_ms": exc.retry_after_ms,
                    "tenant": exc.tenant,
                    "scope": exc.scope,
                })
            except (KeyError, ValueError, TypeError) as exc:
                return _FakeResponse(400, {"error": str(exc)})
            return _FakeResponse(200, {"job_id": job_id})
        if path == "workflows":
            # Workflow DAG submit (ISSUE 19) — same dispatch and error
            # mapping as controller/server.py's POST /v1/workflows.
            from agent_tpu.sched import AdmissionError

            try:
                out = self.controller.submit_workflow(
                    workflow=body,
                    tenant=body.get("tenant"),
                    priority=body.get("priority"),
                    deadline_sec=body.get("deadline_sec"),
                    workflow_id=body.get("workflow_id"),
                )
            except AdmissionError as exc:
                return _FakeResponse(429, {
                    "error": str(exc),
                    "retry_after_ms": exc.retry_after_ms,
                    "tenant": exc.tenant,
                    "scope": exc.scope,
                })
            except (KeyError, ValueError, TypeError) as exc:
                return _FakeResponse(400, {"error": str(exc)})
            except RuntimeError as exc:
                return _FakeResponse(501, {"error": str(exc)})
            return _FakeResponse(200, out)
        return _FakeResponse(404, {"error": f"no route {url}"})


class GatedSession:
    """Wraps a session with an on/off outage switch: while ``down``, posts
    raise ``ChaosTransportError`` without reaching the inner session."""

    def __init__(self, inner: Any) -> None:
        self.inner = inner
        self.down = False
        self.rejected = 0

    def post(self, url: str, json: Any = None, timeout: Any = None):  # noqa: A002
        if self.down:
            self.rejected += 1
            raise ChaosTransportError("chaos: controller outage")
        return self.inner.post(url, json=json, timeout=timeout)


class ChaosSession:
    """Plan-driven transport faults around any session's ``post``.

    Fault order per request: delay → drop_request (never delivered) →
    deliver → duplicate_result (results only: delivered again; the first
    response is returned, so the agent believes one clean post happened
    while the controller saw two) → drop_response (delivered, answer lost)
    → http_500 (delivered, but the agent is told the server failed). The
    post-delivery faults are the interesting ones: they force redelivery of
    results the controller already applied, which epoch fencing and the
    duplicate guard must absorb without double-applying.
    """

    def __init__(
        self,
        inner: Any,
        plan: FaultPlan,
        registry: Any = None,
        recorder: Any = None,
        sleep=time.sleep,
    ) -> None:
        self.inner = inner
        self.plan = plan
        self.recorder = recorder
        self._sleep = sleep
        self._m = (
            registry.counter(
                "chaos_faults_injected_total",
                "Transport faults injected by the chaos session",
                ("fault", "path"),
            )
            if registry is not None
            else None
        )

    def _note(self, fault: str, path: str) -> None:
        if self._m is not None:
            self._m.inc(fault=fault, path=path)
        if self.recorder is not None:
            self.recorder.record("chaos_fault", fault=fault, path=path)

    def post(self, url: str, json: Any = None, timeout: Any = None):  # noqa: A002
        plan = self.plan
        path = _path_of(url)
        if plan.decide("delay"):
            self._note("delay", path)
            self._sleep(plan.draw_delay())
        if plan.decide("drop_request"):
            self._note("drop_request", path)
            raise ChaosTransportError(f"chaos: dropped request to {path}")
        resp = self.inner.post(url, json=json, timeout=timeout)
        if path == "results" and plan.decide("duplicate_result"):
            self._note("duplicate_result", path)
            self.inner.post(url, json=json, timeout=timeout)
        if plan.decide("drop_response"):
            self._note("drop_response", path)
            raise ChaosTransportError(f"chaos: dropped response from {path}")
        if plan.decide("http_500"):
            self._note("http_500", path)
            return _FakeResponse(500, {"error": "chaos: injected 500"})
        return resp
