"""Data plane: byte-offset CSV sharding, the parallel autotuned staging
pool (``staging.py``), and the compact binary shard wire (``wire.py``).

Successor of the reference's skip-scan CSV reader (reference
``ops/csv_shard.py:9-26``), which re-reads every row before ``start_row`` on
each shard — O(N²/shard_size) across a job. Here a quote-aware newline index is
built once per file (natively in C++ when the extension is built, pure Python
otherwise) and every shard is a direct byte-range read.
"""

from agent_tpu.data.csv_index import CsvIndex, read_shard, count_rows

__all__ = ["CsvIndex", "read_shard", "count_rows"]
