"""Compact binary shard wire — the round-4 uint8 raw-byte classify wire
generalized into a codec (ISSUE 6 tentpole).

The lease/result protocol is JSON, and at drain scale the JSON bodies ARE
the tunnel cost: a classify shard's columnar result spells every score as
``0.123456`` decimal text and a summarize shard ships its texts twice (task
in, summaries out) as escaped JSON strings. This module packs the bulk
columns of classify/summarize task and result payloads into one columnar,
length-prefixed, optionally zlib-compressed binary blob that rides the
existing JSON wire base64-encoded under a single ``"__bin__"`` key — no new
endpoints, no content-type change, and the in-process ``LoopbackSession``
path sees the identical envelope.

Blob layout (little-endian throughout)::

    magic  b"AW"
    u8     flags            bit0 = body is zlib-compressed
    body   u8 n_cols, then per column:
             u8 name_len, name utf-8
             u8 kind:
               0 json:     u32 len, utf-8 JSON bytes
               1 strings:  u32 count, u32[count] byte lengths, utf-8 concat
               2 ndarray:  u8 dtype code, u8 ndim, u32[ndim] shape,
                           u32 byte len, raw array bytes

Compression is *adaptive* by default: the body is deflated and kept only if
it shrank (random float columns may not compress; repetitive text columns
crush), so the uncompressed fallback is part of the format, not an error.

**Equivalence contract** — the whole point of the codec is that a binary
drain is bit-identical to a JSON drain once decoded:

- string columns round-trip exact UTF-8 (non-ASCII included);
- integer arrays may be width-shrunk on the wire (int32 column whose values
  fit int8 ships 1 byte/value) — ``tolist()`` of any width yields the same
  Python ints JSON would have carried;
- float columns ship their exact bit patterns and decode via ``tolist()``,
  so an op that would have serialized ``np.round(vals, 6).tolist()`` passes
  the *rounded f32 array* here and the decoded floats are the very same
  widened doubles;
- everything that is not a bulk column lumps into one JSON side-channel
  column (name ``""``), serialized with the same ``json`` semantics as the
  plain wire.

Negotiation (see ``controller/PROTOCOL.CONTRACT.md``): agents advertise
``capabilities.wire_formats = ["b1"]``; a binary-capable controller answers
leases with ``wire: "b1"`` and may encode task payloads; the agent then
encodes result columns. Either side staying silent keeps the other on plain
JSON — old controllers and old agents see byte-identical traffic.
"""

from __future__ import annotations

import base64
import json
import struct
import zlib
from typing import Any, Dict, Optional

import numpy as np

FORMAT = "b1"
FORMATS = (FORMAT,)
# The envelope key on the JSON wire. A payload/result dict carrying it is a
# binary envelope; everything else is legacy JSON.
KEY = "__bin__"

MAGIC = b"AW"
_FLAG_ZLIB = 0x01

_K_JSON, _K_STRS, _K_ARR = 0, 1, 2

_DTYPES = (
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "float32", "float64",
)
_DTYPE_CODE = {np.dtype(d): i for i, d in enumerate(_DTYPES)}

# Ops whose task payloads the controller may binary-encode (their bulk
# column is ``texts``). Results self-select: ops attach columns only for
# their own shard-shaped outputs.
ENCODABLE_OPS = frozenset({"map_classify_tpu", "map_summarize"})


def _shrink_int(arr: np.ndarray) -> np.ndarray:
    """Smallest signed width that holds the values (wire-only: ``tolist()``
    of any int width yields the same Python ints)."""
    if arr.dtype.kind not in "iu" or arr.size == 0:
        return arr
    lo, hi = int(arr.min()), int(arr.max())
    for cand in (np.int8, np.int16, np.int32, np.int64):
        info = np.iinfo(cand)
        if info.min <= lo and hi <= info.max:
            if np.dtype(cand).itemsize >= arr.dtype.itemsize:
                return arr  # never widen (uint8 must not become int16)
            return arr.astype(cand)
    return arr  # uint64 beyond int64 range keeps its own dtype


def encode_blob(cols: Dict[str, Any], compress: Optional[bool] = None) -> bytes:
    """Pack ``cols`` into one blob. Values: ``np.ndarray`` → array column,
    ``list[str]`` → string column, anything else → JSON column.
    ``compress``: None = adaptive (keep zlib only if smaller), True/False
    force. Raises ValueError on unsupported dtypes / oversized names."""
    if len(cols) > 255:
        raise ValueError(f"too many columns ({len(cols)})")
    body = bytearray()
    body += struct.pack("<B", len(cols))
    for name, value in cols.items():
        nb = str(name).encode("utf-8")
        if len(nb) > 255:
            raise ValueError(f"column name too long ({len(nb)} bytes)")
        body += struct.pack("<B", len(nb))
        body += nb
        if isinstance(value, np.ndarray):
            arr = _shrink_int(np.ascontiguousarray(value))
            code = _DTYPE_CODE.get(arr.dtype)
            if code is None:
                raise ValueError(f"unsupported array dtype {arr.dtype}")
            if arr.ndim > 255:
                raise ValueError("array rank > 255")
            data = arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes()
            body += struct.pack("<BBB", _K_ARR, code, arr.ndim)
            body += struct.pack(f"<{arr.ndim}I", *arr.shape)
            body += struct.pack("<I", len(data))
            body += data
        elif isinstance(value, list) and all(
            isinstance(t, str) for t in value
        ):
            encoded = [t.encode("utf-8") for t in value]
            body += struct.pack("<BI", _K_STRS, len(encoded))
            body += np.fromiter(
                (len(b) for b in encoded), dtype="<u4", count=len(encoded)
            ).tobytes()
            body += b"".join(encoded)
        else:
            data = json.dumps(value, separators=(",", ":")).encode("utf-8")
            body += struct.pack("<BI", _K_JSON, len(data))
            body += data
    raw = bytes(body)
    flags = 0
    out = raw
    if compress is not False:
        z = zlib.compress(raw, 6)
        if compress is True or len(z) < len(raw):
            out, flags = z, _FLAG_ZLIB
    return MAGIC + struct.pack("<B", flags) + out


def decode_blob(blob: bytes) -> Dict[str, Any]:
    """Inverse of :func:`encode_blob`, producing JSON-able values (arrays
    come back as nested lists via ``tolist()`` — the decoded dict is exactly
    what the plain JSON wire would have carried). Raises ValueError on any
    malformed input (bad magic, truncation, bad zlib, bad UTF-8)."""
    try:
        if blob[:2] != MAGIC:
            raise ValueError("bad magic")
        flags = blob[2]
        body = blob[3:]
        if flags & _FLAG_ZLIB:
            body = zlib.decompress(body)
        view = memoryview(body)
        pos = 0

        def take(n: int) -> memoryview:
            nonlocal pos
            if pos + n > len(view):
                raise ValueError("truncated blob")
            out = view[pos:pos + n]
            pos += n
            return out

        (n_cols,) = struct.unpack("<B", take(1))
        cols: Dict[str, Any] = {}
        for _ in range(n_cols):
            (name_len,) = struct.unpack("<B", take(1))
            name = bytes(take(name_len)).decode("utf-8")
            (kind,) = struct.unpack("<B", take(1))
            if kind == _K_JSON:
                (n,) = struct.unpack("<I", take(4))
                cols[name] = json.loads(bytes(take(n)).decode("utf-8"))
            elif kind == _K_STRS:
                (count,) = struct.unpack("<I", take(4))
                lens = np.frombuffer(take(4 * count), dtype="<u4")
                total = int(lens.sum())
                data = bytes(take(total))
                out, off = [], 0
                for ln in lens.tolist():
                    out.append(data[off:off + ln].decode("utf-8"))
                    off += ln
                cols[name] = out
            elif kind == _K_ARR:
                code, ndim = struct.unpack("<BB", take(2))
                if code >= len(_DTYPES):
                    raise ValueError(f"unknown dtype code {code}")
                shape = struct.unpack(f"<{ndim}I", take(4 * ndim))
                (n,) = struct.unpack("<I", take(4))
                arr = np.frombuffer(
                    take(n), dtype=np.dtype(_DTYPES[code]).newbyteorder("<")
                ).reshape(shape)
                cols[name] = arr.tolist()
            else:
                raise ValueError(f"unknown column kind {kind}")
        return cols
    except ValueError:
        raise
    except Exception as exc:  # zlib.error, struct.error, Unicode errors, …
        raise ValueError(f"malformed wire blob: {exc}") from exc


def pack_b64(cols: Dict[str, Any], compress: Optional[bool] = None) -> str:
    """Blob → the base64 ASCII string that rides the JSON wire."""
    return base64.b64encode(encode_blob(cols, compress)).decode("ascii")


def unpack_b64(data: str) -> Dict[str, Any]:
    if not isinstance(data, str):
        raise ValueError("wire envelope payload must be a base64 string")
    try:
        blob = base64.b64decode(data.encode("ascii"), validate=True)
    except Exception as exc:  # noqa: BLE001 — binascii.Error, UnicodeError
        raise ValueError(f"bad base64 envelope: {exc}") from exc
    return decode_blob(blob)


# ---- task payloads (controller → agent) ----

def encodable_task(op: str, payload: Any) -> bool:
    """Should the controller binary-encode this task's payload? Only the
    text ops, and only when the payload actually carries a bulk ``texts``
    column (shard-addressed ``source_uri`` payloads are already tiny)."""
    if op not in ENCODABLE_OPS or not isinstance(payload, dict):
        return False
    texts = payload.get("texts")
    return (
        isinstance(texts, list)
        and bool(texts)
        and all(isinstance(t, str) for t in texts)
    )


def encode_task_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """``{texts: […], **rest}`` → ``{"__bin__": <b64>}``. The non-bulk keys
    ride the JSON side-channel column, so the decoded payload is value-equal
    to the original."""
    rest = {k: v for k, v in payload.items() if k != "texts"}
    return {KEY: pack_b64({"texts": payload["texts"], "": rest})}


def is_binary_payload(payload: Any) -> bool:
    return isinstance(payload, dict) and isinstance(payload.get(KEY), str)


def decode_task_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`encode_task_payload`; raises ValueError on a
    malformed envelope (the agent reports it like any malformed task)."""
    cols = unpack_b64(payload[KEY])
    out: Dict[str, Any] = {}
    rest = cols.pop("", None)
    if isinstance(rest, dict):
        out.update(rest)
    out.update(cols)
    return out


# ---- results (agent → controller) ----

def attach_result_columns(
    result: Dict[str, Any],
    cols: Dict[str, Any],
    compress: Optional[bool] = None,
) -> Dict[str, Any]:
    """Op-finalize fast path: hand the bulk columns over as raw arrays /
    string lists instead of ``tolist()``-ing them into the JSON body. The
    decoded result merges the columns back under their own keys."""
    result[KEY] = pack_b64(cols, compress)
    return result


def is_binary_result(result: Any) -> bool:
    return isinstance(result, dict) and isinstance(result.get(KEY), str)


def decode_result(result: Dict[str, Any]) -> Dict[str, Any]:
    """Controller-side decode: the stored result is exactly what a JSON-wire
    agent would have posted (envelope key dropped, columns merged)."""
    cols = unpack_b64(result[KEY])
    out = {k: v for k, v in result.items() if k != KEY}
    rest = cols.pop("", None)
    if isinstance(rest, dict):
        out.update(rest)
    out.update(cols)
    return out
