"""Result-sink utilities: validate and merge the JSONL shard files the model
ops write in ``output_uri`` mode.

A drain leaves ``<op>_rows_<start_row>.jsonl`` files behind (one per shard,
line ``k`` = dataset row ``start_row + k``; see ``_model_common.
write_output_shard``). These helpers are the consumer side of that contract:

- :func:`scan_sink` — inventory a sink directory for one op.
- :func:`validate_sink` — prove the drain is complete: shard starts form the
  expected arithmetic progression, no gaps, no overlaps, per-file row counts
  sum to ``total_rows``.
- :func:`merge_sink` — concatenate the shards into one JSONL in dataset row
  order (streaming; never holds more than one shard in memory).

Also runnable as a CLI:

    python -m agent_tpu.data.sink validate <dir> --op map_summarize \
        --total-rows 10000000
    python -m agent_tpu.data.sink merge <dir> --op map_summarize \
        --out merged.jsonl
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional

_SHARD_RE = re.compile(r"^(?P<op>.+)_rows_(?P<start>\d{12})\.jsonl$")


@dataclass(frozen=True)
class SinkShard:
    path: str
    start_row: int
    n_rows: int


def scan_sink(directory: str, op: str) -> List[SinkShard]:
    """Shard files for ``op`` under ``directory``, sorted by start_row.
    Row counts come from counting lines (the receipts hold the same number;
    the file is the source of truth here)."""
    shards: List[SinkShard] = []
    for name in os.listdir(directory):
        m = _SHARD_RE.match(name)
        if not m or m.group("op") != op:
            continue
        path = os.path.join(directory, name)
        with open(path, "rb") as f:
            n = sum(1 for _ in f)
        shards.append(SinkShard(path, int(m.group("start")), n))
    return sorted(shards, key=lambda s: s.start_row)


def validate_sink(
    directory: str, op: str, total_rows: Optional[int] = None,
    shards: Optional[List[SinkShard]] = None,
) -> Dict[str, object]:
    """Completeness proof for a drained sink → summary dict.

    Raises ValueError naming the first problem: a gap (missing shard), an
    overlap (a shard wrote more rows than the next shard's start allows),
    or a total mismatch. A retried shard is fine — atomic writes mean the
    file holds exactly one shard's rows. ``shards`` lets a caller that
    already scanned (``merge_sink``) validate that exact list — no rescan,
    no window for the file set to change between validation and use.
    """
    if shards is None:
        shards = scan_sink(directory, op)
    if not shards:
        raise ValueError(f"no {op!r} shard files in {directory}")
    if shards[0].start_row != 0:
        raise ValueError(
            f"first shard starts at row {shards[0].start_row}, expected 0"
        )
    expect = 0
    for s in shards:
        if s.start_row > expect:
            raise ValueError(
                f"gap: rows [{expect}, {s.start_row}) missing "
                f"(no shard file before {os.path.basename(s.path)})"
            )
        if s.start_row < expect:
            raise ValueError(
                f"overlap at {os.path.basename(s.path)}: starts at "
                f"{s.start_row} but previous shard covered up to {expect}"
            )
        expect = s.start_row + s.n_rows
    if total_rows is not None and expect != total_rows:
        raise ValueError(
            f"row total mismatch: shards cover {expect} rows, "
            f"expected {total_rows}"
        )
    return {
        "op": op,
        "shards": len(shards),
        "rows": expect,
        "first": shards[0].start_row,
        "last": shards[-1].start_row,
    }


def merge_sink(
    directory: str, op: str, out_path: str,
    total_rows: Optional[int] = None,
) -> Dict[str, object]:
    """Validate then concatenate the shards in dataset row order into
    ``out_path`` (atomic: tmp + rename). One scan: the validated list is
    the list that gets copied (streamed shard by shard)."""
    shards = scan_sink(directory, op)
    summary = validate_sink(directory, op, total_rows, shards=shards)
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as out:
        for shard in shards:
            with open(shard.path, "rb") as f:
                for line in f:
                    out.write(line)
    os.replace(tmp, out_path)
    summary["out"] = out_path
    return summary


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("validate", "merge"):
        p = sub.add_parser(name)
        p.add_argument("directory")
        p.add_argument("--op", required=True)
        p.add_argument("--total-rows", type=int, default=None)
        if name == "merge":
            p.add_argument("--out", required=True)
    args = ap.parse_args(argv)
    try:
        if args.cmd == "validate":
            out = validate_sink(args.directory, args.op, args.total_rows)
        else:
            out = merge_sink(args.directory, args.op, args.out,
                             args.total_rows)
    except ValueError as exc:
        print(json.dumps({"ok": False, "error": str(exc)}))
        return 1
    print(json.dumps({"ok": True, **out}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
