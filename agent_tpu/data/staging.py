"""Parallel autotuned staging pool — the host half of ISSUE 6's tentpole.

The pipelined runner (``agent/pipeline.py``) overlapped *one* stager thread
with the device loop; when an op's ``stage()`` (CSV shard read + fused
tokenize+pad) costs more wall clock than its ``execute()`` dispatch, that
single stager is the pipeline's limiter and the device idles — the exact
input-bound regime the tf.data paper's autotuner targets (PAPERS, arxiv
2101.12127). This module runs N stage workers concurrently:

- a **feeder** thread owns the lease loop (one thread keeps the lease RTT
  serialized and the grant accounting simple) and fans raw tasks into a
  bounded ``task_q``;
- **worker** threads pull tasks, run the op's ``stage()`` phase (pure host
  by contract — no device state), and push staged items into the runner's
  bounded ``staged_q``;
- an **autotuner** (``STAGE_AUTOTUNE``) re-reads the agent's own metrics
  registry — ``task_phase_seconds{phase=stage}`` vs ``{phase=execute}``,
  the measurements the pipeline already records; no new clock — and sizes
  the *effective* parallelism (an adjustable gate, so threads never need
  respawning) and the prefetch depth to the live stage/execute ratio.

Ordering: the feeder enqueues tasks in lease order and a 1-worker pool
preserves it end to end; with N workers staged items may reorder, which the
protocol explicitly permits (results key by ``job_id``). Stage itself is a
pure per-task function, so multi-worker output is bit-identical to
single-worker output — pinned by ``scripts/check_data_plane.py`` in CI.

Shutdown mirrors the single-stager contract: the feeder stops leasing when
``agent.running`` flips, workers drop undrained tasks (the lease TTL
re-queues them), and the LAST worker to exit owns delivering the ``_STOP``
sentinel to the device loop — a lost sentinel would leave the device thread
blocked in ``get()`` forever.
"""

from __future__ import annotations

import math
import os
import queue
import threading
import time
from typing import Any, Callable, Optional, Tuple

from agent_tpu.utils.logging import log
from agent_tpu.utils.retry import jittered

# Auto worker count: min(4, cpu_count) per the tf.data guidance — staging is
# numpy/tokenize-bound, and the device thread + poster need cores too.
DEFAULT_MAX_WORKERS = 4

# Autotuner cadence: re-reading the registry snapshot is cheap but not free.
RETUNE_INTERVAL_SEC = 1.0
# Minimum fresh per-phase samples before a retune acts — two tasks of noise
# must not thrash the worker gate.
RETUNE_MIN_SAMPLES = 3


def default_workers() -> int:
    return max(1, min(DEFAULT_MAX_WORKERS, os.cpu_count() or 1))


def desired_workers(
    stage_sec: float, exec_sec: float, max_workers: int
) -> int:
    """Workers needed so aggregate staging throughput matches the device:
    ``ceil(stage/execute)``, clamped to [1, max_workers]. A zero/unknown
    execute time with real stage cost means the device is starving —
    saturate; with neither measured, stay at 1."""
    if stage_sec <= 0:
        return 1
    if exec_sec <= 0:
        return max_workers
    return max(1, min(max_workers, math.ceil(stage_sec / exec_sec)))


class AdjustableGate:
    """Counting gate whose permit limit can change at runtime — the
    autotuner's lever. Workers park here instead of being torn down, so a
    limit bump takes effect on the very next task."""

    def __init__(self, limit: int) -> None:
        self._cond = threading.Condition()
        self._limit = max(1, int(limit))
        self._active = 0

    @property
    def limit(self) -> int:
        return self._limit

    def set_limit(self, limit: int) -> None:
        with self._cond:
            self._limit = max(1, int(limit))
            self._cond.notify_all()

    def acquire(self, timeout: float = 0.5) -> bool:
        with self._cond:
            if self._active < self._limit:
                self._active += 1
                return True
            self._cond.wait(timeout)
            if self._active < self._limit:
                self._active += 1
                return True
            return False

    def release(self) -> None:
        with self._cond:
            self._active = max(0, self._active - 1)
            self._cond.notify()


class PhaseRatioSampler:
    """Windowed stage/execute seconds-per-task from the agent's metrics
    registry — the regulator reads the obs the pipeline already records
    (``task_phase_seconds`` sums/counts, all ops), never a second clock."""

    def __init__(self, registry: Any) -> None:
        self._registry = registry
        self._last = {"stage": (0.0, 0), "execute": (0.0, 0)}

    def sample(self) -> Optional[Tuple[float, float]]:
        """→ (stage_sec_per_task, execute_sec_per_task) over the window
        since the previous call, or None when too few new samples landed."""
        try:
            fam = self._registry.snapshot().get("task_phase_seconds") or {}
        except Exception:  # noqa: BLE001 — telemetry must never kill staging
            return None
        totals = {"stage": [0.0, 0], "execute": [0.0, 0]}
        for series in fam.get("series", []):
            phase = (series.get("labels") or {}).get("phase")
            if phase in totals:
                totals[phase][0] += float(series.get("sum", 0.0))
                totals[phase][1] += int(series.get("count", 0))
        out = []
        fresh_ok = True
        for phase in ("stage", "execute"):
            s, c = totals[phase]
            ls, lc = self._last[phase]
            ds, dc = s - ls, c - lc
            if dc < RETUNE_MIN_SAMPLES:
                fresh_ok = False
            out.append(ds / dc if dc > 0 else 0.0)
        if not fresh_ok:
            return None
        self._last = {
            "stage": (totals["stage"][0], totals["stage"][1]),
            "execute": (totals["execute"][0], totals["execute"][1]),
        }
        return out[0], out[1]


class StagingPool:
    """Owns the feeder + worker threads in front of a bounded staged queue.

    ``stage_fn(lease_id, task) -> item | None`` is the runner's per-task
    staging function (``PipelineRunner._stage_one``); ``stop_token`` is the
    sentinel the device loop expects exactly once on ``staged_q``.
    """

    def __init__(
        self,
        agent: Any,
        staged_q: "queue.Queue",
        stage_fn: Callable[[str, Any], Any],
        stop_token: Any,
        max_workers: Optional[int] = None,
        autotune: Optional[bool] = None,
        base_depth: int = 2,
    ) -> None:
        self.agent = agent
        self.staged_q = staged_q
        self.stage_fn = stage_fn
        self.stop_token = stop_token
        cfg = agent.config.agent
        self.max_workers = max(
            1, max_workers if max_workers is not None
            else (cfg.stage_workers or default_workers())
        )
        self.autotune = (
            cfg.stage_autotune if autotune is None else bool(autotune)
        )
        self.base_depth = max(1, base_depth)
        # Start saturated: until the first retune window closes there is no
        # ratio to regulate from, and idle workers cost nothing.
        self.gate = AdjustableGate(self.max_workers)
        self.task_q: "queue.Queue" = queue.Queue(
            maxsize=max(2, 2 * self.max_workers)
        )
        self._sampler = PhaseRatioSampler(agent.obs)
        self._last_retune = time.monotonic()
        self._alive_lock = threading.Lock()
        self._workers_alive = 0
        self._g_workers = agent.obs.gauge(
            "stage_pool_workers",
            "Staging-pool effective parallelism (autotuned gate limit)")
        self._g_depth = agent.obs.gauge(
            "stage_prefetch_depth",
            "Staged-queue bound (autotuned prefetch depth)")
        self._g_workers.set(self.gate.limit)
        self._g_depth.set(self.staged_q.maxsize)
        self._feeder = threading.Thread(
            target=self._feed_loop, name="agent-feeder", daemon=True
        )
        self._threads = [self._feeder]
        for i in range(self.max_workers):
            self._threads.append(threading.Thread(
                target=self._worker_loop, name=f"agent-stager-{i}",
                daemon=True,
            ))

    # ---- lifecycle ----

    def start(self) -> None:
        self._workers_alive = self.max_workers
        for t in self._threads:
            t.start()

    def join(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))

    def backlog(self) -> int:
        """Leased-but-not-executed depth (staged + awaiting a worker) — the
        load number the lease capabilities advertise."""
        return self.staged_q.qsize() + self.task_q.qsize()

    # ---- feeder thread (lease loop) ----

    def _feed_loop(self) -> None:
        agent = self.agent
        try:
            while agent.running:
                # The grant ask tracks the live gate limit so an autotuned-up
                # pool doesn't starve on 1-task grants (the controller may
                # still shrink the grant — that stays advisory downward).
                agent.lease_batch_hint = self.gate.limit
                self._maybe_retune()
                try:
                    leased = agent.lease_once()
                except RuntimeError as exc:
                    agent.rate.log("lease", str(exc))
                    time.sleep(agent._lease_retry.next_backoff())
                    continue
                agent._lease_retry.reset()
                if leased is None:
                    time.sleep(jittered(agent.config.agent.idle_sleep_sec))
                    continue
                lease_id, tasks = leased
                for task in tasks:
                    if agent.running:
                        self._put_task((lease_id, task))
                    elif getattr(agent, "draining", False):
                        # Drain (ISSUE 10): hand unstarted tasks back
                        # instead of abandoning them to the lease TTL.
                        agent.release_task(lease_id, task)
        finally:
            # One sentinel per worker, delivered even if the feeder died
            # unexpectedly; the last worker converts them into the device
            # loop's single stop token.
            for _ in range(self.max_workers):
                self._put_task(self.stop_token, force=True)

    def _put_task(self, entry: Any, force: bool = False) -> None:
        while True:
            try:
                self.task_q.put(entry, timeout=0.5)
                return
            except queue.Full:
                if not self.agent.running and not force:
                    self._release_entry(entry)
                    return  # drain aborted; released, or TTL re-queues
                if force and self._workers_alive_count() == 0:
                    return  # nobody left to read the sentinel

    def _workers_alive_count(self) -> int:
        with self._alive_lock:
            return self._workers_alive

    def _release_entry(self, entry: Any) -> None:
        """Hand a dropped ``(lease_id, task)`` back during a graceful drain
        (ISSUE 10) — without this every drop point strands the lease until
        the TTL. A non-draining stop keeps the historical abandon."""
        if entry is self.stop_token or not getattr(
            self.agent, "draining", False
        ):
            return
        try:
            lease_id, task = entry
        except (TypeError, ValueError):
            return
        self.agent.release_task(lease_id, task)

    def release_pending(self) -> int:
        """Drain-release every task still queued for staging after the
        workers exited (a worker that parked at the gate during shutdown
        leaves its queue tail unread). Called by the runner once the pool
        has joined; returns how many were handed back."""
        released = 0
        while True:
            try:
                entry = self.task_q.get_nowait()
            except queue.Empty:
                return released
            if entry is self.stop_token:
                continue
            self._release_entry(entry)
            released += 1

    # ---- worker threads ----

    def _worker_loop(self) -> None:
        agent = self.agent
        try:
            while True:
                try:
                    entry = self.task_q.get(timeout=0.5)
                except queue.Empty:
                    if not agent.running:
                        break
                    continue
                if entry is self.stop_token:
                    break
                lease_id, task = entry
                # The autotuner's lever: workers above the gate limit park
                # here instead of staging, shedding parallelism without
                # tearing threads down.
                dropped = False
                while not self.gate.acquire(timeout=0.5):
                    if not agent.running:
                        self._release_entry(entry)
                        dropped = True  # released, or TTL re-queues
                        break
                if dropped:
                    return
                try:
                    item = self.stage_fn(lease_id, task)
                finally:
                    self.gate.release()
                if item is not None:
                    self._put_staged(item)
        finally:
            last = False
            with self._alive_lock:
                self._workers_alive -= 1
                last = self._workers_alive == 0
            if last:
                # Exactly one stop token for the device loop, from whichever
                # worker dies last (mirrors the single-stager guarantee).
                self.staged_q.put(self.stop_token)

    def _put_staged(self, item: Any) -> None:
        """Blocking put that notices shutdown AND live maxsize changes (the
        autotuner may widen the bound mid-wait; the timeout loop re-reads
        it)."""
        while True:
            try:
                self.staged_q.put(item, timeout=0.5)
                self.agent.m_queue.set(self.staged_q.qsize(), queue="staged")
                return
            except queue.Full:
                if not self.agent.running:
                    if getattr(self.agent, "draining", False):
                        # Staged but never executed: nothing applied, so a
                        # release is correct — the work re-runs elsewhere.
                        self.agent.release_job(
                            item.lease_id, item.job_id, item.epoch,
                            op=item.op,
                        )
                    return  # drain aborted; released, or TTL re-queues

    # ---- autotuner ----

    def _maybe_retune(self) -> None:
        if not self.autotune:
            return
        now = time.monotonic()
        if now - self._last_retune < RETUNE_INTERVAL_SEC:
            return
        self._last_retune = now
        sample = self._sampler.sample()
        if sample is None:
            return
        stage_sec, exec_sec = sample
        want = desired_workers(stage_sec, exec_sec, self.max_workers)
        if want != self.gate.limit:
            log(
                "staging pool retuned",
                workers=want,
                stage_ms=round(stage_sec * 1e3, 2),
                execute_ms=round(exec_sec * 1e3, 2),
            )
            self.gate.set_limit(want)
            self._g_workers.set(want)
        # Prefetch depth rides the worker count: enough slack that every
        # active stager has somewhere to land its item plus one in reserve,
        # never below the configured pipeline depth (queue.Queue reads
        # maxsize under its own mutex on every put, so widening/narrowing
        # here is picked up by the workers' timeout-put loop).
        depth = max(self.base_depth, want + 1)
        if depth != self.staged_q.maxsize:
            self.staged_q.maxsize = depth
            self._g_depth.set(depth)
