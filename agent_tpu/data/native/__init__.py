"""Native (C++) data-plane accelerators, loaded via ctypes.

The reference's native layer was third-party (libedgetpu/tflite, reference
``Dockerfile:9-30``); ours is in-repo: a quote-aware CSV row scanner compiled
lazily from ``csv_scan.cpp``. Everything here is best-effort — callers fall
back to pure Python when the toolchain or the built library is unavailable.
"""

from agent_tpu.data.native.build import native_available, scan_row_offsets_native

__all__ = ["native_available", "scan_row_offsets_native"]
