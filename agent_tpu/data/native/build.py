"""Lazy ctypes build/load of the native CSV scanner (placeholder until the
C++ source lands; returns None so callers use the Python scanner)."""

from __future__ import annotations

from typing import Optional

import numpy as np


def scan_row_offsets_native(path: str) -> Optional[np.ndarray]:
    return None
