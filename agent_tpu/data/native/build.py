"""Lazy g++ build + ctypes load of the native CSV scanner.

The shared object compiles once per source change into a cache directory
(``AGENT_TPU_NATIVE_CACHE`` env, default ``~/.cache/agent_tpu``, falling back
to a temp dir), keyed by a hash of ``csv_scan.cpp`` so edits rebuild and
stale binaries never load. Everything is best-effort: no compiler, failed
compile, or failed load all mean "return None" and callers use the
pure-Python scanner (``csv_index._scan_row_offsets_py``).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "csv_scan.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _cache_dir() -> str:
    d = os.environ.get("AGENT_TPU_NATIVE_CACHE")
    if not d:
        home = os.path.expanduser("~")
        d = (
            os.path.join(home, ".cache", "agent_tpu")
            if os.path.isdir(home)
            else os.path.join(tempfile.gettempdir(), "agent_tpu_native")
        )
    os.makedirs(d, exist_ok=True)
    return d


def _build() -> Optional[str]:
    """Compile csv_scan.cpp → cached .so; returns the path or None."""
    gxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if gxx is None or not os.path.exists(_SRC):
        return None
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"csv_scan_{digest}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    try:
        proc = subprocess.run(
            [gxx, "-O3", "-shared", "-fPIC", _SRC, "-o", tmp],
            capture_output=True,
            timeout=120,
        )
        if proc.returncode != 0:
            return None
        os.replace(tmp, out)  # atomic: concurrent builders race harmlessly
        return out
    except (OSError, subprocess.TimeoutExpired):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        so = _build()
        if so is None:
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.csv_scan_offsets.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ]
            lib.csv_scan_offsets.restype = ctypes.c_int64
            lib.csv_scan_free.argtypes = [ctypes.POINTER(ctypes.c_int64)]
            lib.csv_scan_free.restype = None
            _lib = lib
        except OSError:
            _load_failed = True
        return _lib


def scan_row_offsets_native(path: str) -> Optional[np.ndarray]:
    """Row-start offsets via the C++ scanner, or None to use the Python path."""
    lib = _get_lib()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_int64)()
    n = lib.csv_scan_offsets(os.fsencode(path), ctypes.byref(out))
    if n < 0:
        return None
    try:
        return np.ctypeslib.as_array(out, shape=(n,)).astype(np.int64, copy=True)
    finally:
        lib.csv_scan_free(out)


def native_available() -> bool:
    return _get_lib() is not None
