// Quote-aware CSV row scanner — the native half of agent_tpu.data.csv_index.
//
// One streaming pass over the file: record the byte offset after every
// newline that falls OUTSIDE RFC-4180 double quotes (a doubled "" toggles the
// state twice, net no-op, so no special case is needed). This is the hot loop
// that lets shard reads become seek+read; the Python fallback implements the
// identical semantics (csv_index._scan_row_offsets_py), property-tested for
// agreement in tests/test_csv_native.py.
//
// Built lazily by agent_tpu/data/native/build.py:
//   g++ -O3 -shared -fPIC csv_scan.cpp -o csv_scan.so
// and called through ctypes — no pybind11 dependency.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// Scans `path`; on success mallocs an int64 offsets array (first element 0 =
// start of row 0), stores it in *out, and returns the element count. Returns
// -1 when the file cannot be opened. Caller must csv_scan_free(*out).
int64_t csv_scan_offsets(const char *path, int64_t **out);
void csv_scan_free(int64_t *p);

}  // extern "C"

namespace {
constexpr size_t kBufSize = 4 << 20;  // 4 MiB read chunks

// The loop is memchr-driven rather than byte-at-a-time: glibc's memchr is
// vectorized (AVX2 on this image), so hopping newline→newline scans at
// memory bandwidth instead of ~1 byte/cycle. Quote handling keeps the same
// RFC-4180 semantics as the scalar version (every '"' toggles state; a
// doubled "" toggles twice, net no-op): inside quotes we hop '"'→'"'; outside
// we cache the position of the next '"' in the chunk so quote-free data — the
// common case — costs one extra memchr per 4 MiB, not one per row.
}  // namespace

int64_t csv_scan_offsets(const char *path, int64_t **out) {
  FILE *f = std::fopen(path, "rb");
  if (f == nullptr) return -1;

  size_t cap = 1 << 16;
  int64_t *offs = static_cast<int64_t *>(std::malloc(cap * sizeof(int64_t)));
  unsigned char *buf = static_cast<unsigned char *>(std::malloc(kBufSize));
  if (offs == nullptr || buf == nullptr) {
    std::free(offs);
    std::free(buf);
    std::fclose(f);
    return -1;
  }

  size_t n = 0;
  offs[n++] = 0;
  int64_t pos = 0;
  bool in_quote = false;

  size_t got;
  while ((got = std::fread(buf, 1, kBufSize, f)) > 0) {
    size_t i = 0;
    // Positions of the next '"' / '\n' at or after i, or `got` if none remain
    // in this chunk. Each is valid only while it is >= i and refreshed lazily
    // once i passes it, so every byte of the chunk is memchr-scanned at most
    // once per character class — quote-dense rows stay linear.
    size_t next_q = 0, next_nl = 0;
    bool next_q_valid = false, next_nl_valid = false;
    while (i < got) {
      if (in_quote) {
        const void *q = std::memchr(buf + i, '"', got - i);
        if (q == nullptr) {
          i = got;  // rest of chunk is inside the quoted field
          break;
        }
        i = static_cast<size_t>(static_cast<const unsigned char *>(q) - buf) + 1;
        in_quote = false;
        continue;  // i moved past any cached quote; the < i check refreshes

      }
      if (!next_q_valid || next_q < i) {
        const void *q = std::memchr(buf + i, '"', got - i);
        next_q = q == nullptr
                     ? got
                     : static_cast<size_t>(
                           static_cast<const unsigned char *>(q) - buf);
        next_q_valid = true;
      }
      if (!next_nl_valid || next_nl < i) {
        const void *nl = std::memchr(buf + i, '\n', got - i);
        next_nl = nl == nullptr
                      ? got
                      : static_cast<size_t>(
                            static_cast<const unsigned char *>(nl) - buf);
        next_nl_valid = true;
      }
      const size_t nl_pos = next_nl;
      if (next_q < nl_pos) {
        i = next_q + 1;  // now i > next_q, so the staleness check refreshes
        in_quote = true;
      } else if (nl_pos < got) {
        if (n == cap) {
          cap *= 2;
          int64_t *grown =
              static_cast<int64_t *>(std::realloc(offs, cap * sizeof(int64_t)));
          if (grown == nullptr) {
            std::free(offs);
            std::free(buf);
            std::fclose(f);
            return -1;
          }
          offs = grown;
        }
        offs[n++] = pos + static_cast<int64_t>(nl_pos) + 1;
        i = nl_pos + 1;
      } else {
        i = got;  // no newline and no quote left in this chunk
      }
    }
    pos += static_cast<int64_t>(got);
  }

  std::fclose(f);
  std::free(buf);
  // A file ending in '\n' leaves a trailing offset at EOF — not a row start.
  if (n > 1 && offs[n - 1] >= pos) --n;
  *out = offs;
  return static_cast<int64_t>(n);
}

void csv_scan_free(int64_t *p) { std::free(p); }
