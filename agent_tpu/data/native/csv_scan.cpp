// Quote-aware CSV row scanner — the native half of agent_tpu.data.csv_index.
//
// One streaming pass over the file: record the byte offset after every
// newline that falls OUTSIDE RFC-4180 double quotes (a doubled "" toggles the
// state twice, net no-op, so no special case is needed). This is the hot loop
// that lets shard reads become seek+read; the Python fallback implements the
// identical semantics (csv_index._scan_row_offsets_py), property-tested for
// agreement in tests/test_csv_native.py.
//
// Built lazily by agent_tpu/data/native/build.py:
//   g++ -O3 -shared -fPIC csv_scan.cpp -o csv_scan.so
// and called through ctypes — no pybind11 dependency.

#include <cstdint>
#include <cstdio>
#include <cstdlib>

extern "C" {

// Scans `path`; on success mallocs an int64 offsets array (first element 0 =
// start of row 0), stores it in *out, and returns the element count. Returns
// -1 when the file cannot be opened. Caller must csv_scan_free(*out).
int64_t csv_scan_offsets(const char *path, int64_t **out);
void csv_scan_free(int64_t *p);

}  // extern "C"

namespace {
constexpr size_t kBufSize = 1 << 20;  // 1 MiB read chunks
}

int64_t csv_scan_offsets(const char *path, int64_t **out) {
  FILE *f = std::fopen(path, "rb");
  if (f == nullptr) return -1;

  size_t cap = 1 << 16;
  int64_t *offs = static_cast<int64_t *>(std::malloc(cap * sizeof(int64_t)));
  unsigned char *buf = static_cast<unsigned char *>(std::malloc(kBufSize));
  if (offs == nullptr || buf == nullptr) {
    std::free(offs);
    std::free(buf);
    std::fclose(f);
    return -1;
  }

  size_t n = 0;
  offs[n++] = 0;
  int64_t pos = 0;
  bool in_quote = false;

  size_t got;
  while ((got = std::fread(buf, 1, kBufSize, f)) > 0) {
    for (size_t i = 0; i < got; ++i) {
      const unsigned char b = buf[i];
      if (b == '"') {
        in_quote = !in_quote;
      } else if (b == '\n' && !in_quote) {
        if (n == cap) {
          cap *= 2;
          int64_t *grown =
              static_cast<int64_t *>(std::realloc(offs, cap * sizeof(int64_t)));
          if (grown == nullptr) {
            std::free(offs);
            std::free(buf);
            std::fclose(f);
            return -1;
          }
          offs = grown;
        }
        offs[n++] = pos + static_cast<int64_t>(i) + 1;
      }
    }
    pos += static_cast<int64_t>(got);
  }

  std::fclose(f);
  std::free(buf);
  // A file ending in '\n' leaves a trailing offset at EOF — not a row start.
  if (n > 1 && offs[n - 1] >= pos) --n;
  *out = offs;
  return static_cast<int64_t>(n);
}

void csv_scan_free(int64_t *p) { std::free(p); }
