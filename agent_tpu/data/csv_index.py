"""Quote-aware byte-offset row index for CSV files.

Design: one linear scan per file builds ``offsets[i]`` = byte offset of the
start of row ``i`` (row 0 is the header), honoring RFC-4180 quoting so newlines
inside quoted fields do not split rows (the reference's ``csv.DictReader``
skip-scan got this right but paid an O(start_row) scan per shard, reference
``ops/csv_shard.py:18-24``). Shards then become ``file.seek`` + one bounded
read — O(shard bytes) regardless of position, which is what lets the host side
keep a TPU fed (BASELINE.json: "csv_shard.py streams shards straight into HBM
with host-side double buffering").

The scan itself prefers the native C++ scanner (``agent_tpu.data.native``),
falling back to the pure-Python chunked scanner transparently.
"""

from __future__ import annotations

import csv
import io
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

_CHUNK = 1 << 20  # 1 MiB scan chunks

# Default rows per shard (reference ``ops/csv_shard.py:62``) — the single
# definition every shard-addressed op shares.
DEFAULT_SHARD_SIZE = 100


def _scan_row_offsets_py(path: str) -> np.ndarray:
    """Vectorized quote-aware scan → int64 array of row-start offsets.

    Per chunk: numpy finds every quote and newline position at once; the
    number of quotes *before* each newline (``searchsorted``) plus the
    carried-in quote parity decides which newlines are row boundaries —
    a '"' inside a quoted field has odd parity and is skipped. ~2 orders of
    magnitude faster than a per-byte Python loop (the round-1 bottleneck).
    """
    parts: List[np.ndarray] = [np.zeros(1, dtype=np.int64)]
    quote_parity = 0  # quotes seen so far, mod 2, carried across chunks
    pos = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            arr = np.frombuffer(chunk, dtype=np.uint8)
            q_idx = np.flatnonzero(arr == 0x22)  # '"'
            n_idx = np.flatnonzero(arr == 0x0A)  # '\n'
            if n_idx.size:
                quotes_before = np.searchsorted(q_idx, n_idx, side="left")
                outside = ((quotes_before + quote_parity) % 2) == 0
                parts.append(n_idx[outside].astype(np.int64) + pos + 1)
            quote_parity = (quote_parity + q_idx.size) % 2
            pos += len(chunk)
    offsets = np.concatenate(parts)
    # Drop a trailing offset pointing at EOF (file ends with newline).
    if len(offsets) > 1 and offsets[-1] >= pos:
        offsets = offsets[:-1]
    return offsets


def _scan_row_offsets(path: str) -> np.ndarray:
    try:
        from agent_tpu.data.native import scan_row_offsets_native

        out = scan_row_offsets_native(path)
        if out is not None:
            return out
    except Exception:  # noqa: BLE001 — native path is best-effort by design
        pass
    return _scan_row_offsets_py(path)


@dataclass(frozen=True)
class _Key:
    path: str
    size: int
    mtime_ns: int


class CsvIndex:
    """Per-file row index with process-wide caching.

    The cache is keyed by (path, size, mtime) so a rewritten file re-indexes —
    the same invalidation idea as the reference's model-path-keyed interpreter
    singleton (reference ``ops/_tpu_runtime.py:8-13,42-43``), applied to data.
    """

    _cache: Dict[_Key, "CsvIndex"] = {}
    _lock = threading.Lock()

    def __init__(self, path: str, offsets: np.ndarray, size: int) -> None:
        self.path = path
        self.offsets = offsets  # row-start byte offsets; row 0 = header
        self.size = size

    @classmethod
    def for_file(cls, path: str) -> "CsvIndex":
        st = os.stat(path)
        key = _Key(os.path.abspath(path), st.st_size, st.st_mtime_ns)
        with cls._lock:
            idx = cls._cache.get(key)
        if idx is not None:
            return idx
        offsets = _scan_row_offsets(path)
        idx = cls(path, offsets, st.st_size)
        with cls._lock:
            if len(cls._cache) > 64:  # bound memory; files are re-indexable
                cls._cache.clear()
            cls._cache[key] = idx
        return idx

    @property
    def n_data_rows(self) -> int:
        """Rows excluding the header line."""
        return max(0, len(self.offsets) - 1)

    def header(self) -> List[str]:
        raw = self._read_range(0, 1)
        return next(csv.reader(io.StringIO(raw)), [])

    def _read_range(self, start_row: int, n_rows: int) -> str:
        """Read the raw bytes spanning rows [start_row, start_row + n_rows)."""
        if n_rows <= 0 or start_row >= len(self.offsets):
            return ""
        begin = int(self.offsets[start_row])
        end_idx = start_row + n_rows
        end = int(self.offsets[end_idx]) if end_idx < len(self.offsets) else self.size
        with open(self.path, "rb") as f:
            f.seek(begin)
            return f.read(end - begin).decode("utf-8", errors="replace")

    def read_dict_rows(self, start_row: int, shard_size: int) -> List[Dict[str, str]]:
        """Data rows [start_row, start_row+shard_size) as dicts (header keys).

        ``start_row`` counts data rows from 0, matching the reference contract
        (reference ``ops/csv_shard.py:9-26`` DictReader semantics).
        """
        start_row = max(0, start_row)
        n = min(shard_size, self.n_data_rows - start_row)
        if n <= 0:
            return []
        header = self.header()
        body = self._read_range(start_row + 1, n)  # +1: skip header row
        reader = csv.reader(io.StringIO(body))
        return [dict(zip(header, row)) for row in reader]


def read_shard(path: str, start_row: int, shard_size: int) -> List[Dict[str, str]]:
    return CsvIndex.for_file(path).read_dict_rows(start_row, shard_size)


def resolve_shard_payload(payload: Dict) -> Tuple[str, int, int]:
    """Validate the shared CSV-shard payload keys → (path, start_row,
    shard_size); raises ValueError on bad input.

    One definition of the shard-addressing contract for every op that accepts
    it (``read_csv_shard`` and ``map_classify_tpu``'s drain mode) — URI
    schemes or default changes land here once.
    """
    source_uri = payload.get("source_uri")
    if not isinstance(source_uri, str) or not source_uri:
        raise ValueError("source_uri is required and must be a non-empty string")
    start_row = payload.get("start_row", 0)
    if isinstance(start_row, bool) or not isinstance(start_row, int) or start_row < 0:
        raise ValueError("start_row must be a non-negative int")
    shard_size = payload.get("shard_size", DEFAULT_SHARD_SIZE)
    if isinstance(shard_size, bool) or not isinstance(shard_size, int) or shard_size <= 0:
        raise ValueError("shard_size must be a positive int")
    path = source_uri[len("file://"):] if source_uri.startswith("file://") else source_uri
    return path, start_row, shard_size


def count_rows(path: str) -> int:
    return CsvIndex.for_file(path).n_data_rows


def read_shard_column(
    payload: Dict, field_payload_key: str, default_field: str
) -> List[str]:
    """Shard-addressed payload → one column of the shard, for drain-mode ops
    (classify, summarize, and risk_accumulate must treat the same CSV
    identically).

    ``field_payload_key`` names the payload key that selects the column
    (``"text_field"`` for the text ops, ``"field"`` for risk_accumulate).

    Error contract: malformed payload keys raise ValueError (deterministic
    caller error → soft ``bad_input``); shard-level integrity problems (empty
    shard, missing column) raise RuntimeError and I/O problems raise OSError —
    both must surface as *failed* task results so the controller retries and
    then visibly fails, never as soft results that drop the shard's rows.
    """
    field = payload.get(field_payload_key, default_field)
    if not isinstance(field, str) or not field:
        raise ValueError(f"{field_payload_key} must be a non-empty string")
    path, start_row, shard_size = resolve_shard_payload(payload)
    rows = read_shard(path, start_row, shard_size)
    if not rows:
        raise RuntimeError(
            f"shard [{start_row}, {start_row + shard_size}) of {path!r} is empty"
        )
    missing = sum(1 for r in rows if field not in r)
    if missing:
        raise RuntimeError(
            f"column {field!r} missing from {missing} rows of {path!r}"
        )
    return [r[field] for r in rows]


def read_shard_texts(payload: Dict, default_field: str = "text") -> List[str]:
    """The text-op flavor of :func:`read_shard_column` (``text_field`` key)."""
    return read_shard_column(payload, "text_field", default_field)
