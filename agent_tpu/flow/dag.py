"""Workflow DAG validation and expansion (ISSUE 19 tentpole, part 1).

A workflow is a fan-out/fan-in graph of *stages* submitted as one unit —
e.g. tokenize -> N classify shards -> risk_accumulate -> summarize report.
This module is the pure half of the engine:

- ``parse_workflow`` validates the submit document (acyclic, known ops,
  bounded stage count and fan-out width, sane per-stage knobs) and returns
  a frozen ``WorkflowSpec``.
- ``expand_workflow`` lowers the spec into ``PlannedJob``s — ordinary
  controller jobs with *generalized* dep edges. Every planned job carries
  the job-id-level ``after`` list the controller's existing dep-gating
  already understands, so the two-party ``__collect_partials__`` special
  case (MPMD summarize, disagg prefill->decode) becomes just a DAG of
  depth 2.
- ``critical_path_lengths`` computes, per stage, the longest remaining
  path to a sink (in stages). The scheduler uses it for
  critical-path-first ordering: within a priority tier the stage with the
  most downstream work drains first, which for a linear chain degenerates
  to plain FIFO (pinned by a property test in ``tests/test_flow.py``).

The controller (``controller/core.py``) owns the stateful half: journaling
the graph, replay, the single workflow trace tree, DependencyFailed
cascades, and partition placement (whole-DAG by graph id).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

DEFAULT_MAX_STAGES = 32
DEFAULT_MAX_WIDTH = 64

# Stage names become job-id components (``{workflow_id}-{stage}[-{i}]``) and
# trace span names; keep them to a shell/URL-safe charset.
_STAGE_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


class DagError(ValueError):
    """Invalid workflow document — maps to HTTP 400 at the front door."""


@dataclass(frozen=True)
class StageSpec:
    """One validated stage of a workflow graph."""

    name: str
    op: str
    payload: Dict[str, Any] = field(default_factory=dict)
    after: Tuple[str, ...] = ()
    fan_out: int = 1
    priority: Optional[int] = None       # None -> workflow default
    required_labels: Dict[str, Any] = field(default_factory=dict)
    max_attempts: Optional[int] = None   # None -> controller default
    # Deliver upstream results as ``payload["partials"]`` at lease time
    # (the generalized ``__collect_partials__`` contract). On by default
    # for dependent stages; a stage that only wants ordering can opt out.
    collect: bool = True


@dataclass(frozen=True)
class WorkflowSpec:
    """A validated, acyclic workflow graph."""

    stages: Tuple[StageSpec, ...]

    def by_name(self) -> Dict[str, StageSpec]:
        return {s.name: s for s in self.stages}


@dataclass(frozen=True)
class PlannedJob:
    """One expanded stage instance — an ordinary controller job to be."""

    job_id: str
    stage: str
    op: str
    payload: Dict[str, Any]
    after: Tuple[str, ...]          # upstream JOB ids (not stage names)
    priority: int
    critical_path: int              # longest remaining path, in stages
    required_labels: Dict[str, Any]
    max_attempts: Optional[int]


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise DagError(msg)


def parse_workflow(
    doc: Any,
    known_ops: Sequence[str],
    max_stages: int = DEFAULT_MAX_STAGES,
    max_width: int = DEFAULT_MAX_WIDTH,
) -> WorkflowSpec:
    """Validate a submit document -> ``WorkflowSpec``; raise ``DagError``."""
    _require(isinstance(doc, dict), "workflow must be an object")
    raw_stages = doc.get("stages")
    _require(
        isinstance(raw_stages, list) and len(raw_stages) > 0,
        "workflow.stages must be a non-empty list",
    )
    _require(
        len(raw_stages) <= max_stages,
        f"workflow has {len(raw_stages)} stages; limit is {max_stages} "
        "(FLOW_MAX_STAGES)",
    )
    ops = set(known_ops)
    names: set = set()
    stages: List[StageSpec] = []
    for i, raw in enumerate(raw_stages):
        _require(isinstance(raw, dict), f"stage[{i}] must be an object")
        name = raw.get("name")
        _require(
            isinstance(name, str) and bool(_STAGE_NAME_RE.match(name)),
            f"stage[{i}].name must match {_STAGE_NAME_RE.pattern}",
        )
        _require(name not in names, f"duplicate stage name {name!r}")
        names.add(name)
        op = raw.get("op")
        _require(isinstance(op, str) and op != "", f"stage {name!r}: op required")
        _require(
            op in ops,
            f"stage {name!r}: unknown op {op!r}; known ops: {sorted(ops)}",
        )
        payload = raw.get("payload", {})
        _require(
            isinstance(payload, dict), f"stage {name!r}: payload must be an object"
        )
        after_raw = raw.get("after", [])
        _require(
            isinstance(after_raw, (list, tuple))
            and all(isinstance(a, str) for a in after_raw),
            f"stage {name!r}: after must be a list of stage names",
        )
        _require(
            len(set(after_raw)) == len(after_raw),
            f"stage {name!r}: duplicate entries in after",
        )
        fan_out = raw.get("fan_out", 1)
        _require(
            isinstance(fan_out, int) and not isinstance(fan_out, bool)
            and 1 <= fan_out <= max_width,
            f"stage {name!r}: fan_out must be an int in [1, {max_width}] "
            "(FLOW_MAX_WIDTH)",
        )
        priority = raw.get("priority")
        if priority is not None:
            _require(
                isinstance(priority, int) and not isinstance(priority, bool)
                and 0 <= priority <= 9,
                f"stage {name!r}: priority must be an int in [0, 9]",
            )
        labels = raw.get("required_labels", {})
        _require(
            isinstance(labels, dict)
            and all(
                isinstance(v, (str, int, float, bool)) for v in labels.values()
            ),
            f"stage {name!r}: required_labels must map to scalars",
        )
        max_attempts = raw.get("max_attempts")
        if max_attempts is not None:
            _require(
                isinstance(max_attempts, int) and not isinstance(max_attempts, bool)
                and max_attempts >= 1,
                f"stage {name!r}: max_attempts must be an int >= 1",
            )
        collect = raw.get("collect", True)
        _require(
            isinstance(collect, bool), f"stage {name!r}: collect must be a bool"
        )
        stages.append(
            StageSpec(
                name=name,
                op=op,
                payload=dict(payload),
                after=tuple(after_raw),
                fan_out=fan_out,
                priority=priority,
                required_labels=dict(labels),
                max_attempts=max_attempts,
                collect=collect,
            )
        )
    for st in stages:
        for dep in st.after:
            _require(
                dep in names, f"stage {st.name!r}: after references unknown "
                f"stage {dep!r}"
            )
            _require(dep != st.name, f"stage {st.name!r} depends on itself")
    spec = WorkflowSpec(stages=tuple(stages))
    toposort_stages(spec)  # raises DagError on cycles
    return spec


def toposort_stages(spec: WorkflowSpec) -> List[str]:
    """Kahn's algorithm over stage names; raise ``DagError`` on a cycle.

    Ties resolve in declaration order so expansion is deterministic."""
    indeg: Dict[str, int] = {s.name: len(s.after) for s in spec.stages}
    dependents: Dict[str, List[str]] = {s.name: [] for s in spec.stages}
    for s in spec.stages:
        for dep in s.after:
            dependents[dep].append(s.name)
    order: List[str] = []
    ready = [s.name for s in spec.stages if indeg[s.name] == 0]
    while ready:
        name = ready.pop(0)
        order.append(name)
        for d in dependents[name]:
            indeg[d] -= 1
            if indeg[d] == 0:
                ready.append(d)
    if len(order) != len(spec.stages):
        cyclic = sorted(n for n, d in indeg.items() if d > 0)
        raise DagError(f"workflow graph has a cycle through stages {cyclic}")
    return order


def critical_path_lengths(spec: WorkflowSpec) -> Dict[str, int]:
    """Stage -> longest remaining path to a sink, counted in stages.

    A sink stage scores 1; each upstream stage scores 1 + the max over its
    dependents. For a linear chain of k stages the values are k..1 — i.e.
    strictly decreasing along submit order, so critical-path-first ordering
    equals plain FIFO there (the property test's invariant)."""
    dependents: Dict[str, List[str]] = {s.name: [] for s in spec.stages}
    for s in spec.stages:
        for dep in s.after:
            dependents[dep].append(s.name)
    cp: Dict[str, int] = {}
    for name in reversed(toposort_stages(spec)):
        downstream = [cp[d] for d in dependents[name]]
        cp[name] = 1 + (max(downstream) if downstream else 0)
    return cp


def stage_job_ids(workflow_id: str, stage: StageSpec) -> List[str]:
    """Deterministic job ids for a stage's instances (replay-stable)."""
    if stage.fan_out == 1:
        return [f"{workflow_id}-{stage.name}"]
    return [f"{workflow_id}-{stage.name}-{i}" for i in range(stage.fan_out)]


def expand_workflow(
    spec: WorkflowSpec,
    workflow_id: str,
    default_priority: int = 5,
) -> List[PlannedJob]:
    """Lower a validated spec into per-instance ``PlannedJob``s.

    Fan-in semantics: every instance of a dependent stage waits on EVERY
    instance of each upstream stage (``after`` lists all upstream job ids,
    in stage-declaration then shard order — the order ``partials`` will be
    materialized in at lease time). Fan-out instances get
    ``fan_index``/``fan_out`` stamped into their payload so ops can shard
    deterministically."""
    by_name = spec.by_name()
    ids: Dict[str, List[str]] = {
        s.name: stage_job_ids(workflow_id, s) for s in spec.stages
    }
    cp = critical_path_lengths(spec)
    planned: List[PlannedJob] = []
    for name in toposort_stages(spec):
        st = by_name[name]
        upstream: List[str] = []
        for dep in st.after:
            upstream.extend(ids[dep])
        for i, job_id in enumerate(ids[name]):
            payload = dict(st.payload)
            if st.fan_out > 1:
                payload["fan_index"] = i
                payload["fan_out"] = st.fan_out
            if upstream and st.collect:
                payload["__collect_partials__"] = True
            planned.append(
                PlannedJob(
                    job_id=job_id,
                    stage=name,
                    op=st.op,
                    payload=payload,
                    after=tuple(upstream),
                    priority=st.priority if st.priority is not None
                    else default_priority,
                    critical_path=cp[name],
                    required_labels=dict(st.required_labels),
                    max_attempts=st.max_attempts,
                )
            )
    return planned


def graph_doc(spec: WorkflowSpec) -> Dict[str, Any]:
    """JSON-able graph document — journaled with the workflow so replay,
    standby promotion, and ``GET /v1/workflows/{id}`` all see the same
    structure the submitter sent (post-validation)."""
    return {
        "stages": [
            {
                "name": s.name,
                "op": s.op,
                "payload": s.payload,
                "after": list(s.after),
                "fan_out": s.fan_out,
                "priority": s.priority,
                "required_labels": s.required_labels,
                "max_attempts": s.max_attempts,
                "collect": s.collect,
            }
            for s in spec.stages
        ]
    }


def spec_from_graph_doc(doc: Dict[str, Any]) -> WorkflowSpec:
    """Rebuild a spec from a journaled ``graph_doc`` (trusted — already
    validated at submit time; replay must not re-reject it if limits
    tightened between restarts)."""
    stages = tuple(
        StageSpec(
            name=raw["name"],
            op=raw["op"],
            payload=dict(raw.get("payload", {})),
            after=tuple(raw.get("after", ())),
            fan_out=int(raw.get("fan_out", 1)),
            priority=raw.get("priority"),
            required_labels=dict(raw.get("required_labels", {})),
            max_attempts=raw.get("max_attempts"),
            collect=bool(raw.get("collect", True)),
        )
        for raw in doc.get("stages", [])
    )
    return WorkflowSpec(stages=stages)
