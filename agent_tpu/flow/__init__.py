"""Workflow DAG engine + content-addressed result cache (ISSUE 19).

Two first-class subsystems grown out of ideas the repo already believed in:

- ``flow.dag`` promotes the controller's two-party dep-gating
  (``__collect_partials__``, which powered MPMD summarize and the disagg
  prefill->decode handoff) to arbitrary fan-out/fan-in workflow graphs
  submitted as ONE unit (``POST /v1/workflows``), following the
  dataflow-graph staging model of tf.data (arxiv 2101.12127).
- ``flow.result_cache`` promotes the serving bucketer's byte-bucket key and
  the PR 16 prefix cache to a general content-addressed result cache keyed
  ``stable_hash(op, canonical_payload, model_version)`` — at millions of
  users duplicate work dominates, and the same cache serves both planes
  (batch shards and ``/v1/infer`` requests).

The controller owns the runtime wiring (journal replay, trace trees, usage
billing, partition placement); these modules stay pure so they can be
property-tested in isolation.
"""

from agent_tpu.flow.dag import (  # noqa: F401
    DagError,
    PlannedJob,
    StageSpec,
    WorkflowSpec,
    critical_path_lengths,
    expand_workflow,
    graph_doc,
    parse_workflow,
    toposort_stages,
)
from agent_tpu.flow.result_cache import ResultCache  # noqa: F401
