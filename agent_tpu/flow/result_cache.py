"""Content-addressed result cache (ISSUE 19 tentpole, part 2).

A bounded LRU keyed ``stable_hash(op, canonical_payload, model_version)``.
The controller consults it at workflow-stage submit/lease time for
cacheable ops (deterministic, marked in the op registry's
``CACHEABLE_OPS``) and at the ``/v1/infer`` front door before bucketing —
plain ``POST /v1/jobs`` submits always execute (submitted == executed is
the pre-DAG contract) but their results still populate the cache. Hits
bill at cache price in the
usage ledger and journal as cache-hit terminal result events, so replay
reproduces the exact same stored bytes whether a result was computed or
served from cache.

Keying follows the partition layer's ``stable_hash`` idiom (keyed blake2b
over a canonical byte string) rather than Python ``hash()`` so keys are
stable across processes — the same property that makes rendezvous placement
and the serving bucketer's byte-bucket key replay-safe. The payload is
canonicalized as compact sorted-key JSON; non-JSON values degrade to
``repr`` (deterministic for the scalar/list/dict payloads the ops take).

Invalidation is by model-version bump: the version participates in the key,
and ``set_model_version`` additionally drops the old generation eagerly so
capacity is never wasted on unreachable entries.

Thread-safe; all mutation happens under one lock (same discipline as the
controller's single-lock core). Stored results are deep-copied on both put
and get so callers can never alias cache memory — bit-identical replay
depends on entries being immutable once stored.
"""

from __future__ import annotations

import copy
import hashlib
import json
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional


def canonical_payload(payload: Dict[str, Any]) -> str:
    """Deterministic byte-stable JSON encoding of an op payload."""
    return json.dumps(
        payload, sort_keys=True, separators=(",", ":"), default=repr
    )


def result_key(op: str, payload: Dict[str, Any], model_version: str) -> str:
    """``stable_hash(op, canonical_payload, model_version)`` -> hex digest."""
    blob = "\x1f".join((op, model_version, canonical_payload(payload)))
    return hashlib.blake2b(blob.encode("utf-8"), digest_size=16).hexdigest()


class ResultCache:
    """Bounded LRU of op results, content-addressed and version-fenced."""

    def __init__(self, capacity: int = 4096, model_version: str = "v1") -> None:
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.capacity = max(0, int(capacity))
        self.model_version = str(model_version)
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.invalidations = 0

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def key(self, op: str, payload: Dict[str, Any]) -> str:
        return result_key(op, payload, self.model_version)

    def get(self, op: str, payload: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Return a private copy of the cached result, or None (counted)."""
        if not self.enabled:
            return None
        k = self.key(op, payload)
        with self._lock:
            entry = self._entries.get(k)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(k)
            self.hits += 1
            return copy.deepcopy(entry)

    def put(self, op: str, payload: Dict[str, Any], result: Any) -> bool:
        """Store a computed result; evict LRU past capacity. Non-dict
        results are refused (the op contract returns dicts; anything else
        is a malformed agent report and must not be replayed from cache)."""
        if not self.enabled or not isinstance(result, dict):
            return False
        k = self.key(op, payload)
        with self._lock:
            self._entries[k] = copy.deepcopy(result)
            self._entries.move_to_end(k)
            self.puts += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
        return True

    def set_model_version(self, version: str) -> bool:
        """Model-version bump: fence the key space AND drop the old
        generation (entries under the old version are unreachable — keeping
        them would silently shrink effective capacity)."""
        version = str(version)
        if version == self.model_version:
            return False
        with self._lock:
            self.model_version = version
            self.invalidations += 1
            self._entries.clear()
        return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "puts": self.puts,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "hit_rate": (self.hits / lookups) if lookups else 0.0,
                "model_version": self.model_version,
            }
