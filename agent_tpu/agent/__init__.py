"""Agent runtime — the lease→execute→report control loop.

Successor of reference ``app.py``: identical wire protocol (SURVEY.md §2.9 —
``POST /v1/leases`` / ``POST /v1/results``, 204-means-idle, ``job_epoch``
fencing), same env-var config surface, same error/backoff/drain semantics —
but dispatching through the real op registry (``load_ops``) instead of a
private inline table, shipping a *dynamic* worker profile from ``sizing``
instead of a hardcoded dict, and handing ops an ``OpContext`` that carries the
device runtime so a leased task executes as a batched SPMD program on the mesh.
"""

from agent_tpu.agent.app import Agent, main

__all__ = ["Agent", "main"]
