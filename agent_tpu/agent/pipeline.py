"""Pipelined drain: host-side double buffering around the device loop.

The serial agent loop pays, per task: lease RTT → CSV read + tokenize/pad →
device compute → serialize + result RTT, all on one thread — so the device
idles while the host stages and posts (the round-2 gap: drain < pure-op
throughput). This runner overlaps them (BASELINE.json north star: "streams
shards straight into HBM with host-side double buffering"):

- **stager thread**: leases tasks and runs each op's ``stage`` phase (payload
  validation, shard read, fused tokenize+pad → numpy) feeding a bounded
  queue of depth ``pipeline_depth``; the bound is the backpressure that keeps
  staging ~one shard ahead of the device instead of reading the whole
  dataset into RAM.
- **device (calling) thread**: pops staged work and runs the op's ``execute``
  phase — every device touch stays on this one thread, preserving the
  single-owner invariant the reference called the "TPU RULE" (reference
  ``app.py:286``; SURVEY.md §5.2). No forks, no process pools.
- **poster thread**: runs ``finalize`` — which for the model ops also pays
  the deferred device→host result fetch (reading a ``jax.Array`` is
  thread-safe; only dispatch is owner-bound), then numpy → JSON shapes —
  and posts the result over its own HTTP session. Deferring the fetch here
  is what lets the device thread dispatch shard i+1 while shard i's
  round trip is in flight; the bounded post queue caps how many unfetched
  shards may be pinned at once.

Ops advertise phases as attributes on their registered handler
(``fn.stage/.execute/.finalize``, see ``ops/map_classify_tpu.py``); ops
without them run monolithically on the device thread, so the pipeline is
safe for every op.

Wire-protocol semantics are unchanged: same lease/result bodies, same
structured errors, same epoch fencing. Results may post out of task order —
the protocol never required ordering (results are keyed by job_id).
Multi-host slices don't use this runner: leader/follower lockstep broadcast
serializes by design (``agent/app.py``).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from agent_tpu.obs.trace import TraceContext, new_span_id, use_context
from agent_tpu.utils.errors import structured_error
from agent_tpu.utils.logging import log
from agent_tpu.utils.retry import jittered


@dataclass
class _Item:
    """One leased task moving through the pipeline."""

    lease_id: str
    job_id: str
    epoch: Any
    op: str
    payload: Dict[str, Any]
    ctx: Any
    t_start: float
    fn: Any = None
    staged: Any = None            # op state between stage and execute
    executed: Any = None          # op state between execute and finalize
    result: Any = None            # terminal result (skips later phases)
    status: str = "succeeded"
    error: Any = None
    monolithic: bool = False      # op has no phase hooks
    # Tracing (ISSUE 5): the task's trace context (trace_id = job_id,
    # span_parent = the controller's lease span) and the phase boundary the
    # queue span is measured from. The runner's existing wall-clock phase
    # measurements become spans — no second clock.
    trace_id: Any = None
    span_parent: Any = None
    t_staged: float = 0.0         # when staging finished (queue-span start)


_STOP = object()

# How long a shutting-down device thread keeps waiting for the poster to free
# a post-queue slot before giving up (wedged-poster escape; see _put_post).
SHUTDOWN_GRACE_SEC = 30.0


class PipelineRunner:
    """Owns the stager/poster threads around the caller's device loop.

    ``runner.run()`` blocks on the device loop until ``agent.running`` flips
    false (signal handler or test), then drains both queues so no leased task
    is dropped on shutdown — same graceful-drain contract as the serial loop.
    """

    def __init__(self, agent, depth: int = 2) -> None:
        self.agent = agent
        self.depth = max(1, depth)
        self.staged_q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        # Bounded like staged_q: with deferred fetch (ops returning
        # unfetched device arrays from execute), this bound is what caps
        # in-flight shards — an unbounded post queue would pin device
        # output buffers without limit when the poster falls behind.
        self.post_q: "queue.Queue" = queue.Queue(maxsize=self.depth + 1)
        # Live load advertisement (ISSUE 4): the stager's lease polls ship
        # the CURRENT staged-queue occupancy in capabilities.queue_depth, so
        # the controller's fair scheduler can shrink this agent's grants and
        # steer bulk shards to idler agents while we're backed up. (The obs
        # gauge lags a queue transition; the qsize read does not.)
        agent.staged_depth_fn = self.staged_q.qsize
        self.tasks_posted = 0
        self._stager = threading.Thread(
            target=self._stage_loop, name="agent-stager", daemon=True
        )
        self._poster = threading.Thread(
            target=self._post_loop, name="agent-poster", daemon=True
        )

    # ---- stager thread ----

    def _stage_one(self, lease_id: str, task: Any) -> Optional[_Item]:
        agent = self.agent
        t0 = time.perf_counter()
        # Shared resolution (Agent.resolve_task): malformed-task salvage and
        # the UnknownOp shape are single-sourced with the serial loop.
        job_id, op, payload, epoch, fn, resolve_error = agent.resolve_task(task)
        attempt = task.get("attempt") if isinstance(task, dict) else None
        trace_id, span_parent = agent.task_trace(task)
        if resolve_error is not None:
            if job_id is None:
                return None
            return _Item(
                lease_id, job_id, epoch, op, {}, None, t0,
                status="failed", error=resolve_error,
                trace_id=trace_id, span_parent=span_parent,
            )

        item = _Item(
            lease_id, job_id, epoch, op, payload,
            agent._op_context(job_id, lease_id=lease_id, attempt=attempt,
                              parent_span_id=span_parent),
            t0, fn=fn, trace_id=trace_id, span_parent=span_parent,
        )
        stage = getattr(fn, "stage", None)
        if stage is None:
            item.monolithic = True
            item.t_staged = time.perf_counter()
            return item
        try:
            phase, value = stage(payload, item.ctx)
        except Exception as exc:  # noqa: BLE001 — same contract as run_task
            item.status = "failed"
            item.error = structured_error(exc)
            agent.rate.log("exec", "stage raised", op=op, type=type(exc).__name__)
            agent.recorder.record(
                "error", phase="stage", job_id=job_id, op=op,
                lease_id=lease_id, attempt=attempt,
                type=type(exc).__name__, message=str(exc)[:200],
            )
            return item
        item.t_staged = time.perf_counter()
        agent.m_phase.observe(
            item.t_staged - t0,
            exemplar={"trace_id": job_id}, op=op, phase="stage",
        )
        # The runner's existing stage measurement, as a span (ISSUE 5).
        agent.trace_span(
            "stage", trace_id, span_parent,
            start_mono=t0, duration_s=item.t_staged - t0, op=op,
        )
        agent.recorder.record(
            "phase", phase="staged", job_id=job_id, op=op,
            lease_id=lease_id, attempt=attempt,
        )
        if phase == "done":
            item.result = value
        else:
            item.staged = value
        return item

    def _stage_loop(self) -> None:
        agent = self.agent
        try:
            while agent.running:
                try:
                    leased = agent.lease_once()
                except RuntimeError as exc:
                    agent.rate.log("lease", str(exc))
                    # Shared retry policy (utils/retry.py): decorrelated
                    # jittered backoff instead of the old flat sleep.
                    time.sleep(agent._lease_retry.next_backoff())
                    continue
                agent._lease_retry.reset()
                if leased is None:
                    time.sleep(jittered(agent.config.agent.idle_sleep_sec))
                    continue
                lease_id, tasks = leased
                for task in tasks:
                    if not agent.running:
                        break
                    item = self._stage_one(lease_id, task)
                    if item is not None:
                        self._put_bounded(item)  # blocks at depth; backpressure
        finally:
            # The sentinel must reach the device loop even if this thread
            # dies unexpectedly — a lost sentinel would leave the device
            # thread blocked in get() forever, a hung agent holding the TPU.
            self.staged_q.put(_STOP)

    def _put_bounded(self, item: Any) -> None:
        """Blocking put that still notices shutdown: if the device loop died
        with the queue full, a plain put() would deadlock the stager."""
        while True:
            try:
                self.staged_q.put(item, timeout=0.5)
                self.agent.m_queue.set(self.staged_q.qsize(), queue="staged")
                return
            except queue.Full:
                if not self.agent.running:
                    return  # drain aborted; lease TTL re-queues the task

    # ---- device (calling) thread ----

    def _put_post(self, item: Any) -> bool:
        """Blocking put into the bounded post queue. Blocking here is the
        backpressure that caps in-flight shards (ops defer their device→host
        fetch to the poster, so every queued item pins device buffers).

        Escapes: a dead poster (blocking would deadlock), or shutdown with a
        poster that has stopped draining (e.g. wedged in a fetch on a hung
        device) — a graceful drain keeps consuming and frees a slot well
        inside the grace window, so normal shutdown still posts everything."""
        waited = 0.0
        while True:
            try:
                self.post_q.put(item, timeout=0.5)
                self.agent.m_queue.set(self.post_q.qsize(), queue="post")
                return True
            except queue.Full:
                if not self._poster.is_alive():
                    return False  # lease TTL re-queues the task
                if self.agent.running:
                    # Normal backpressure: only POST-shutdown waiting counts
                    # against the grace window, else a slow-but-draining
                    # poster could have an item dropped the instant
                    # shutdown begins.
                    waited = 0.0
                    continue
                waited += 0.5
                if waited >= SHUTDOWN_GRACE_SEC:
                    return False  # wedged poster during shutdown

    def _execute_loop(self) -> None:
        agent = self.agent
        try:
            while True:
                # Busy/idle attribution (the tf.data question — is the input
                # stage or the accelerator the limiter?): time blocked here
                # is device idle; time inside the op dispatch is device busy.
                t_wait = time.perf_counter()
                item = self.staged_q.get()
                agent.m_device_idle.inc(time.perf_counter() - t_wait)
                if item is _STOP:
                    break
                agent.m_queue.set(self.staged_q.qsize(), queue="staged")
                if item.result is not None or item.status == "failed":
                    self._put_post(item)
                    continue
                t_exec = time.perf_counter()
                if item.t_staged:
                    # Time spent waiting in the staged queue — the
                    # backpressure gap between host staging and the device.
                    agent.trace_span(
                        "queue", item.trace_id, item.span_parent,
                        start_mono=item.t_staged,
                        duration_s=t_exec - item.t_staged, op=item.op,
                    )
                # Pre-minted so compile spans emitted inside the dispatch
                # (executor cache misses) parent to this execute span.
                exec_span_id = new_span_id()
                trace_ctx = TraceContext(
                    trace_id=item.trace_id or item.job_id,
                    parent_span_id=exec_span_id,
                    tracer=agent.tracer,
                    registry=agent.obs,
                    process=agent._process_name(),
                )
                try:
                    # profiled_call covers phased ops too — PROFILE_DIR
                    # traces capture the device phase either way (§5.1).
                    with use_context(trace_ctx):
                        if item.monolithic:
                            item.result = agent.profiled_call(
                                item.op,
                                lambda i=item: i.fn(i.payload, i.ctx),
                            )
                        else:
                            item.executed = agent.profiled_call(
                                item.op,
                                lambda i=item: i.fn.execute(i.staged, i.ctx),
                            )
                except Exception as exc:  # noqa: BLE001 — op error → failed
                    item.status = "failed"
                    item.error = structured_error(exc)
                    agent.rate.log("exec", "op raised", op=item.op,
                                   type=type(exc).__name__)
                    agent.recorder.record(
                        "error", phase="execute", job_id=item.job_id,
                        op=item.op, lease_id=item.lease_id,
                        type=type(exc).__name__, message=str(exc)[:200],
                    )
                dt = time.perf_counter() - t_exec
                agent.m_device_busy.inc(dt)
                agent.m_phase.observe(
                    dt, exemplar={"trace_id": item.job_id},
                    op=item.op, phase="execute",
                )
                agent.trace_span(
                    "execute", item.trace_id, item.span_parent,
                    span_id=exec_span_id, start_mono=t_exec, duration_s=dt,
                    op=item.op, status=item.status,
                )
                agent.recorder.record(
                    "phase", phase="executed", job_id=item.job_id,
                    op=item.op, lease_id=item.lease_id,
                    status=item.status,
                )
                self._put_post(item)
        finally:
            self._put_post(_STOP)  # same lost-sentinel guard as the stager

    # ---- poster thread ----

    def _post_loop(self) -> None:
        agent = self.agent
        # Own HTTP session: requests.Session is not thread-safe, and the
        # stager is concurrently POSTing leases on the agent's session.
        session = None
        try:
            import requests

            session = requests.Session()
        except Exception:  # noqa: BLE001 — stub sessions in tests
            pass
        while True:
            item = self.post_q.get()
            if item is _STOP:
                # Shutdown: force one last redelivery pass past the backoff
                # window; what stays undeliverable survives in the on-disk
                # spool (when configured) for the next incarnation.
                agent.flush_spool(session=session, force=True)
                break
            agent.m_queue.set(self.post_q.qsize(), queue="post")
            t_fin = time.perf_counter()
            try:
                if item.executed is not None:
                    item.result = item.fn.finalize(item.executed, item.ctx)
            except Exception as exc:  # noqa: BLE001
                item.status = "failed"
                item.error = structured_error(exc)
                item.result = None
                agent.recorder.record(
                    "error", phase="finalize", job_id=item.job_id,
                    op=item.op, lease_id=item.lease_id,
                    type=type(exc).__name__, message=str(exc)[:200],
                )
            finalize_s = time.perf_counter() - t_fin
            agent.m_phase.observe(
                finalize_s, exemplar={"trace_id": item.job_id},
                op=item.op, phase="finalize",
            )
            duration_ms = (time.perf_counter() - item.t_start) * 1000.0
            if item.ctx is not None:
                timings = item.ctx.tags.setdefault("timings", {})
                # Stamped here because finalize cannot time its own return;
                # rides the result body so scrape-side attribution sees the
                # poster-thread cost too.
                timings["finalize_ms"] = round(finalize_s * 1000.0, 3)
                # queue/fetch come from the op's own timings; stage/execute/
                # finalize were measured wall-clock by the runner threads
                # (observing both views would double-count those phases).
                agent.record_phase_timings(
                    item.op, timings, keys=("queue_ms", "fetch_ms"),
                    trace_id=item.job_id,
                )
            if isinstance(item.result, dict):
                item.result.setdefault("duration_ms", duration_ms)
                if item.ctx is not None:
                    if item.ctx.tags.get("timings"):
                        item.result.setdefault(
                            "timings", item.ctx.tags["timings"]
                        )
                    item.result.setdefault(
                        "trace", item.ctx.tags.get("trace")
                    )
            agent.post_result(
                item.lease_id, item.job_id, item.epoch, item.status,
                result=item.result, error=item.error, session=session,
                op=item.op,
            )
            # Poster-thread cost as one span: finalize (incl. the deferred
            # device→host fetch) + the result post. Ships on the NEXT post
            # or the final metrics-only flush.
            agent.trace_span(
                "post", item.trace_id, item.span_parent,
                start_mono=t_fin,
                duration_s=time.perf_counter() - t_fin,
                op=item.op, status=item.status,
                finalize_ms=round(finalize_s * 1e3, 3),
            )
            # Spooled redelivery rides the poster cadence (backoff-gated
            # inside flush_spool) — the pipelined drain heals from a
            # controller blip the same way the serial loop does.
            agent.flush_spool(session=session)
            self.tasks_posted += 1
            agent.tasks_done += 1
            agent.m_tasks.inc(op=item.op, status=item.status)
            agent.recorder.record(
                "phase", phase="posted", job_id=item.job_id, op=item.op,
                lease_id=item.lease_id, status=item.status,
                duration_ms=round(duration_ms, 3),
            )
            agent.note_progress(queues={
                "staged_q": self.staged_q.qsize(),
                "post_q": self.post_q.qsize(),
            })

    # ---- lifecycle ----

    def run(self) -> None:
        # The runtime must exist before the stager reads mesh metadata, and
        # it must be built HERE: this is the device-owning thread.
        if self.agent.runtime is None:
            from agent_tpu.runtime.runtime import get_runtime

            self.agent.runtime = get_runtime(self.agent.config.device)
        log("pipelined drain up", depth=self.depth)
        self._stager.start()
        self._poster.start()
        try:
            self._execute_loop()   # device work stays on the caller's thread
        finally:
            self.agent.running = False
            self._stager.join(timeout=30)
            self._poster.join(timeout=30)
            # Final telemetry flush (metrics-only lease): the last shard's
            # finalize postdates the stager's last real poll, so without
            # this the fleet view would miss the drain's tail.
            self.agent.push_metrics()
        log("pipelined drain stopped", tasks_posted=self.tasks_posted)
