"""Pipelined drain: host-side double buffering around the device loop.

The serial agent loop pays, per task: lease RTT → CSV read + tokenize/pad →
device compute → serialize + result RTT, all on one thread — so the device
idles while the host stages and posts (the round-2 gap: drain < pure-op
throughput). This runner overlaps them (BASELINE.json north star: "streams
shards straight into HBM with host-side double buffering"):

- **staging pool** (ISSUE 6, ``data/staging.py``): a feeder thread owns the
  lease loop and N autotuned workers run op ``stage`` phases (payload
  validation, shard read, fused tokenize+pad → numpy) *concurrently* into a
  bounded queue of depth ``pipeline_depth`` (the autotuner may widen it);
  the bound is the backpressure that keeps staging ~one shard ahead of the
  device instead of reading the whole dataset into RAM. ``STAGE_WORKERS=1``
  reproduces the old single-stager pipeline exactly.
- **device (calling) thread**: pops staged work and runs the op's ``execute``
  phase — every device touch stays on this one thread, preserving the
  single-owner invariant the reference called the "TPU RULE" (reference
  ``app.py:286``; SURVEY.md §5.2). No forks, no process pools. With
  ``FEED_DOUBLE_BUFFER`` (default on) it also *pre-feeds* the next staged
  item's host→device transfer (``jax.device_put`` is async and this is the
  owning thread) before dispatching the current item, so the device never
  waits on a transfer between shards.
- **poster thread**: runs ``finalize`` — which for the model ops also pays
  the deferred device→host result fetch (reading a ``jax.Array`` is
  thread-safe; only dispatch is owner-bound), then numpy → JSON shapes —
  and posts the result over its own HTTP session. Deferring the fetch here
  is what lets the device thread dispatch shard i+1 while shard i's
  round trip is in flight; the bounded post queue caps how many unfetched
  shards may be pinned at once.

Ops advertise phases as attributes on their registered handler
(``fn.stage/.execute/.finalize``, see ``ops/map_classify_tpu.py``); ops
without them run monolithically on the device thread, so the pipeline is
safe for every op.

Wire-protocol semantics are unchanged: same lease/result bodies, same
structured errors, same epoch fencing. Results may post out of task order —
the protocol never required ordering (results are keyed by job_id).
Multi-host slices don't use this runner: leader/follower lockstep broadcast
serializes by design (``agent/app.py``).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from agent_tpu.obs.trace import TraceContext, new_span_id, use_context
from agent_tpu.obs.usage import stamp_usage
from agent_tpu.utils.errors import structured_error
from agent_tpu.utils.logging import log


@dataclass
class _Item:
    """One leased task moving through the pipeline."""

    lease_id: str
    job_id: str
    epoch: Any
    op: str
    payload: Dict[str, Any]
    ctx: Any
    t_start: float
    fn: Any = None
    staged: Any = None            # op state between stage and execute
    executed: Any = None          # op state between execute and finalize
    result: Any = None            # terminal result (skips later phases)
    status: str = "succeeded"
    error: Any = None
    monolithic: bool = False      # op has no phase hooks
    # Tracing (ISSUE 5): the task's trace context (trace_id = job_id,
    # span_parent = the controller's lease span) and the phase boundary the
    # queue span is measured from. The runner's existing wall-clock phase
    # measurements become spans — no second clock.
    trace_id: Any = None
    span_parent: Any = None
    t_staged: float = 0.0         # when staging finished (queue-span start)
    # Continuous serving (ISSUE 15): the engine handle while this item's
    # requests ride the running batch, and the admit instant the execute
    # span measures from.
    serve_handle: Any = None
    t_serve0: float = 0.0


_STOP = object()

# How long a shutting-down device thread keeps waiting for the poster to free
# a post-queue slot before giving up (wedged-poster escape; see _put_post).
SHUTDOWN_GRACE_SEC = 30.0


class PipelineRunner:
    """Owns the staging pool + poster thread around the caller's device loop.

    ``runner.run()`` blocks on the device loop until ``agent.running`` flips
    false (signal handler or test), then drains both queues so no leased task
    is dropped on shutdown — same graceful-drain contract as the serial loop.
    """

    def __init__(
        self,
        agent,
        depth: int = 2,
        workers: Optional[int] = None,
        autotune: Optional[bool] = None,
        double_buffer: Optional[bool] = None,
    ) -> None:
        self.agent = agent
        self.depth = max(1, depth)
        self.staged_q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        # Bounded like staged_q: with deferred fetch (ops returning
        # unfetched device arrays from execute), this bound is what caps
        # in-flight shards — an unbounded post queue would pin device
        # output buffers without limit when the poster falls behind.
        self.post_q: "queue.Queue" = queue.Queue(maxsize=self.depth + 1)
        # Staging pool (ISSUE 6): the feeder thread owns the lease loop and
        # N autotuned workers run stage() concurrently; workers/autotune
        # default from config (STAGE_WORKERS / STAGE_AUTOTUNE).
        from agent_tpu.data.staging import StagingPool

        self._pool = StagingPool(
            agent, self.staged_q, self._stage_one, _STOP,
            max_workers=workers, autotune=autotune, base_depth=self.depth,
        )
        # Double-buffered device feed (FEED_DOUBLE_BUFFER): pre-issue the
        # next item's host→device transfer while the current one executes.
        self.double_buffer = (
            agent.config.agent.feed_double_buffer
            if double_buffer is None else bool(double_buffer)
        )
        # Live load advertisement (ISSUE 4): lease polls ship the CURRENT
        # leased-but-unexecuted backlog (staged + queued-for-staging) in
        # capabilities.queue_depth, so the controller's fair scheduler can
        # shrink this agent's grants and steer bulk shards to idler agents
        # while we're backed up. (The obs gauge lags a queue transition;
        # the qsize read does not.)
        agent.staged_depth_fn = self._pool.backlog
        self.tasks_posted = 0
        self._poster = threading.Thread(
            target=self._post_loop, name="agent-poster", daemon=True
        )

    # ---- staging (run on the pool's worker threads) ----

    def _stage_one(self, lease_id: str, task: Any) -> Optional[_Item]:
        agent = self.agent
        t0 = time.perf_counter()
        # Shared resolution (Agent.resolve_task): malformed-task salvage and
        # the UnknownOp shape are single-sourced with the serial loop.
        job_id, op, payload, epoch, fn, resolve_error = agent.resolve_task(task)
        attempt = task.get("attempt") if isinstance(task, dict) else None
        trace_id, span_parent = agent.task_trace(task)
        if resolve_error is not None:
            if job_id is None:
                return None
            return _Item(
                lease_id, job_id, epoch, op, {}, None, t0,
                status="failed", error=resolve_error,
                trace_id=trace_id, span_parent=span_parent,
            )

        item = _Item(
            lease_id, job_id, epoch, op, payload,
            agent._op_context(job_id, lease_id=lease_id, attempt=attempt,
                              parent_span_id=span_parent,
                              tenant=task.get("tenant")
                              if isinstance(task, dict) else None),
            t0, fn=fn, trace_id=trace_id, span_parent=span_parent,
        )
        stage = getattr(fn, "stage", None)
        if stage is None:
            item.monolithic = True
            item.t_staged = time.perf_counter()
            return item
        try:
            phase, value = stage(payload, item.ctx)
        except Exception as exc:  # noqa: BLE001 — same contract as run_task
            item.status = "failed"
            item.error = structured_error(exc)
            agent.rate.log("exec", "stage raised", op=op, type=type(exc).__name__)
            agent.recorder.record(
                "error", phase="stage", job_id=job_id, op=op,
                lease_id=lease_id, attempt=attempt,
                type=type(exc).__name__, message=str(exc)[:200],
            )
            return item
        item.t_staged = time.perf_counter()
        agent.m_phase.observe(
            item.t_staged - t0,
            exemplar={"trace_id": job_id}, op=op, phase="stage",
        )
        # Host-side usage attribution (ISSUE 9): stage seconds ride the
        # result's usage block next to the device seconds the execute loop
        # stamps.
        stamp_usage(item.ctx.tags, host_s=item.t_staged - t0)
        # The runner's existing stage measurement, as a span (ISSUE 5).
        agent.trace_span(
            "stage", trace_id, span_parent,
            start_mono=t0, duration_s=item.t_staged - t0, op=op,
        )
        agent.recorder.record(
            "phase", phase="staged", job_id=job_id, op=op,
            lease_id=lease_id, attempt=attempt,
        )
        if phase == "done":
            item.result = value
        else:
            item.staged = value
        return item

    # ---- device (calling) thread ----

    def _put_post(self, item: Any) -> bool:
        """Blocking put into the bounded post queue. Blocking here is the
        backpressure that caps in-flight shards (ops defer their device→host
        fetch to the poster, so every queued item pins device buffers).

        Escapes: a dead poster (blocking would deadlock), or shutdown with a
        poster that has stopped draining (e.g. wedged in a fetch on a hung
        device) — a graceful drain keeps consuming and frees a slot well
        inside the grace window, so normal shutdown still posts everything."""
        waited = 0.0
        while True:
            try:
                self.post_q.put(item, timeout=0.5)
                self.agent.m_queue.set(self.post_q.qsize(), queue="post")
                return True
            except queue.Full:
                if not self._poster.is_alive():
                    return False  # lease TTL re-queues the task
                if self.agent.running:
                    # Normal backpressure: only POST-shutdown waiting counts
                    # against the grace window, else a slow-but-draining
                    # poster could have an item dropped the instant
                    # shutdown begins.
                    waited = 0.0
                    continue
                waited += 0.5
                if waited >= SHUTDOWN_GRACE_SEC:
                    return False  # wedged poster during shutdown

    def _prefeed(self, item: Any) -> None:
        """Double-buffered device feed (ISSUE 6): start the NEXT item's
        host→device transfer before the current item's execute dispatch.
        ``jax.device_put`` is async and this is the owning thread, so the
        transfer overlaps the in-flight compute and the op's own
        ``put_batch`` later passes the already-placed arrays through without
        a copy. Only the well-known staged-chunk layout
        (``state["chunks"] = [(ids, lengths, n), …]`` of numpy arrays) is
        pre-fed; anything else stays untouched — this is purely an
        optimization and must never fail an item."""
        import numpy as np

        runtime = self.agent.runtime
        if (
            runtime is None or item.monolithic or item.staged is None
            or item.result is not None or item.status == "failed"
        ):
            return
        state = item.staged
        chunks = state.get("chunks") if isinstance(state, dict) else None
        if not isinstance(chunks, list):
            return
        try:
            fed = []
            for chunk in chunks:
                if (
                    isinstance(chunk, (tuple, list)) and len(chunk) == 3
                    and isinstance(chunk[0], np.ndarray)
                    and isinstance(chunk[1], np.ndarray)
                ):
                    fed.append((
                        runtime.put_batch(chunk[0]),
                        runtime.put_batch(chunk[1]),
                        chunk[2],
                    ))
                else:
                    fed.append(chunk)
            state["chunks"] = fed
        except Exception:  # noqa: BLE001 — the op re-puts on execute anyway
            pass

    def _serve_admit(self, item: Any, serving: list) -> None:
        """Join a serving item's requests to the continuous decode engine:
        prefill runs now (a batched compiled step on this, the device
        thread), the decode iterations run in :meth:`_serve_pump_once`
        interleaved with everything else the loop does."""
        agent = self.agent
        t0 = time.perf_counter()
        item.t_serve0 = t0
        try:
            item.serve_handle = item.fn.serve_admit(item.staged, item.ctx)
        except Exception as exc:  # noqa: BLE001 — op error → failed
            item.status = "failed"
            item.error = structured_error(exc)
            agent.rate.log("exec", "serve admit raised", op=item.op,
                           type=type(exc).__name__)
            agent.recorder.record(
                "error", phase="execute", job_id=item.job_id, op=item.op,
                lease_id=item.lease_id, type=type(exc).__name__,
                message=str(exc)[:200],
            )
            self._put_post(item)
            return
        # Prefill is device time; the decode iterations bill per pump.
        agent.note_device_time(
            item.op, time.perf_counter() - t0,
            item.ctx.tags if item.ctx is not None else None,
        )
        agent.recorder.record(
            "phase", phase="serve_admitted", job_id=item.job_id, op=item.op,
            lease_id=item.lease_id,
        )
        serving.append(item)

    def _serve_pump_once(self, serving: list) -> None:
        """One decode iteration for every distinct engine with items in
        flight (several leased jobs share one engine — pumping it once
        advances all their slots), then post the items whose requests all
        finished. Finished sequences freed their slots inside the engine
        step, so backlogged requests joined BETWEEN iterations."""
        agent = self.agent
        engines: Dict[int, Any] = {}
        for item in serving:
            engines.setdefault(id(item.serve_handle["engine"]), item)
        t0 = time.perf_counter()
        occupancy = 0
        for item in engines.values():
            occupancy = max(occupancy, item.fn.serve_pump(item.serve_handle))
        if engines:
            first = next(iter(engines.values()))
            # Decode-iteration device time, attributed once per pump (the
            # overlapped items share the very same dispatch).
            agent.note_device_time(first.op, time.perf_counter() - t0, None)
            agent.m_serve_occupancy.set(occupancy)
        for item in [
            it for it in serving if it.fn.serve_done(it.serve_handle)
        ]:
            serving.remove(item)
            try:
                item.executed = item.fn.serve_collect(item.serve_handle)
            except Exception as exc:  # noqa: BLE001
                item.status = "failed"
                item.error = structured_error(exc)
                agent.recorder.record(
                    "error", phase="execute", job_id=item.job_id,
                    op=item.op, lease_id=item.lease_id,
                    type=type(exc).__name__, message=str(exc)[:200],
                )
            item.serve_handle = None
            dt = time.perf_counter() - item.t_serve0
            agent.m_phase.observe(
                dt, exemplar={"trace_id": item.job_id},
                op=item.op, phase="execute",
            )
            agent.trace_span(
                "execute", item.trace_id, item.span_parent,
                start_mono=item.t_serve0, duration_s=dt,
                op=item.op, status=item.status,
            )
            agent.recorder.record(
                "phase", phase="executed", job_id=item.job_id, op=item.op,
                lease_id=item.lease_id, status=item.status,
            )
            self._put_post(item)
        if not serving:
            agent.m_serve_occupancy.set(0)

    def _execute_loop(self) -> None:
        agent = self.agent
        pending: Any = None
        # Continuous-serving items currently riding a decode engine
        # (ISSUE 15): the loop interleaves one engine iteration per pass
        # with ordinary staged work, so interactive decode keeps stepping
        # while bulk shards stage and new serving jobs join between steps.
        serving: list = []
        stopping = False
        try:
            while True:
                item = None
                if pending is not None:
                    item, pending = pending, None
                elif not stopping:
                    if serving:
                        # Decode in flight: never block on the queue — an
                        # empty poll just means this pass is pure decode.
                        try:
                            item = self.staged_q.get_nowait()
                        except queue.Empty:
                            item = None
                    else:
                        # Busy/idle attribution (the tf.data question — is
                        # the input stage or the accelerator the limiter?):
                        # time blocked here is device idle; time inside the
                        # op dispatch is device busy.
                        t_wait = time.perf_counter()
                        item = self.staged_q.get()
                        agent.m_device_idle.inc(time.perf_counter() - t_wait)
                if item is _STOP:
                    # Keep pumping until in-flight serving work posts —
                    # a leased request must answer even through shutdown.
                    stopping = True
                    item = None
                if item is not None:
                    self._execute_item(item, serving)
                    pending = self._peeked
                    self._peeked = None
                if serving:
                    self._serve_pump_once(serving)
                if stopping and not serving and pending is None:
                    break
        finally:
            self._put_post(_STOP)  # same lost-sentinel guard as the stager

    _peeked: Any = None

    def _execute_item(self, item: Any, serving: list) -> None:
        agent = self.agent
        agent.m_queue.set(self.staged_q.qsize(), queue="staged")
        if item.result is not None or item.status == "failed":
            self._put_post(item)
            return
        if getattr(item.fn, "serve_admit", None) is not None \
                and not item.monolithic:
            self._serve_admit(item, serving)
            return
        if self.double_buffer:
            # Peek-ahead: grab the next staged item (if any) and issue its
            # transfers now, so they run under the current item's execute.
            # The popped item is handed back to the loop via _peeked and
            # consumed on the next iteration — never lost.
            try:
                peeked = self.staged_q.get_nowait()
            except queue.Empty:
                peeked = None
            if peeked is not None and peeked is not _STOP:
                self._prefeed(peeked)
            self._peeked = peeked
        t_exec = time.perf_counter()
        if item.t_staged:
            # Time spent waiting in the staged queue — the
            # backpressure gap between host staging and the device.
            agent.trace_span(
                "queue", item.trace_id, item.span_parent,
                start_mono=item.t_staged,
                duration_s=t_exec - item.t_staged, op=item.op,
            )
        # Pre-minted so compile spans emitted inside the dispatch
        # (executor cache misses) parent to this execute span.
        exec_span_id = new_span_id()
        trace_ctx = TraceContext(
            trace_id=item.trace_id or item.job_id,
            parent_span_id=exec_span_id,
            tracer=agent.tracer,
            registry=agent.obs,
            process=agent._process_name(),
        )
        try:
            # profiled_call covers phased ops too — PROFILE_DIR
            # traces capture the device phase either way (§5.1).
            with use_context(trace_ctx):
                if item.monolithic:
                    item.result = agent.profiled_call(
                        item.op,
                        lambda i=item: i.fn(i.payload, i.ctx),
                    )
                else:
                    item.executed = agent.profiled_call(
                        item.op,
                        lambda i=item: i.fn.execute(i.staged, i.ctx),
                    )
        except Exception as exc:  # noqa: BLE001 — op error → failed
            item.status = "failed"
            item.error = structured_error(exc)
            agent.rate.log("exec", "op raised", op=item.op,
                           type=type(exc).__name__)
            agent.recorder.record(
                "error", phase="execute", job_id=item.job_id,
                op=item.op, lease_id=item.lease_id,
                type=type(exc).__name__, message=str(exc)[:200],
            )
        dt = time.perf_counter() - t_exec
        # Per-op device attribution + duty/MFU rollup (ISSUE 8).
        agent.note_device_time(
            item.op, dt,
            item.ctx.tags if item.ctx is not None else None,
        )
        agent.m_phase.observe(
            dt, exemplar={"trace_id": item.job_id},
            op=item.op, phase="execute",
        )
        agent.trace_span(
            "execute", item.trace_id, item.span_parent,
            span_id=exec_span_id, start_mono=t_exec, duration_s=dt,
            op=item.op, status=item.status,
        )
        agent.recorder.record(
            "phase", phase="executed", job_id=item.job_id,
            op=item.op, lease_id=item.lease_id,
            status=item.status,
        )
        self._put_post(item)

    # ---- poster thread ----

    def _post_loop(self) -> None:
        agent = self.agent
        # Own HTTP session: requests.Session is not thread-safe, and the
        # feeder is concurrently POSTing leases on the agent's session.
        # ``post_session_factory`` overrides (bench wire-byte counting,
        # loopback soaks) — it must return a session safe for THIS thread.
        session = None
        factory = getattr(agent, "post_session_factory", None)
        if factory is not None:
            session = factory()
        else:
            try:
                import requests

                session = requests.Session()
            except Exception:  # noqa: BLE001 — stub sessions in tests
                pass
        while True:
            item = self.post_q.get()
            if item is _STOP:
                # Shutdown: force one last redelivery pass past the backoff
                # window; what stays undeliverable survives in the on-disk
                # spool (when configured) for the next incarnation.
                agent.flush_spool(session=session, force=True)
                break
            agent.m_queue.set(self.post_q.qsize(), queue="post")
            t_fin = time.perf_counter()
            try:
                if item.executed is not None:
                    item.result = item.fn.finalize(item.executed, item.ctx)
            except Exception as exc:  # noqa: BLE001
                item.status = "failed"
                item.error = structured_error(exc)
                item.result = None
                agent.recorder.record(
                    "error", phase="finalize", job_id=item.job_id,
                    op=item.op, lease_id=item.lease_id,
                    type=type(exc).__name__, message=str(exc)[:200],
                )
            finalize_s = time.perf_counter() - t_fin
            agent.m_phase.observe(
                finalize_s, exemplar={"trace_id": item.job_id},
                op=item.op, phase="finalize",
            )
            duration_ms = (time.perf_counter() - item.t_start) * 1000.0
            if item.ctx is not None:
                # Poster-thread host seconds join the stage stamp (ISSUE 9).
                stamp_usage(item.ctx.tags, host_s=finalize_s)
                timings = item.ctx.tags.setdefault("timings", {})
                # Stamped here because finalize cannot time its own return;
                # rides the result body so scrape-side attribution sees the
                # poster-thread cost too.
                timings["finalize_ms"] = round(finalize_s * 1000.0, 3)
                # queue/fetch come from the op's own timings; stage/execute/
                # finalize were measured wall-clock by the runner threads
                # (observing both views would double-count those phases).
                agent.record_phase_timings(
                    item.op, timings, keys=("queue_ms", "fetch_ms"),
                    trace_id=item.job_id,
                )
            if isinstance(item.result, dict):
                item.result.setdefault("duration_ms", duration_ms)
                if item.ctx is not None:
                    if item.ctx.tags.get("timings"):
                        item.result.setdefault(
                            "timings", item.ctx.tags["timings"]
                        )
                    item.result.setdefault(
                        "trace", item.ctx.tags.get("trace")
                    )
                    if item.ctx.tags.get("usage"):
                        # Usage block (ISSUE 9): what the controller's
                        # showback ledger bills for this task.
                        item.result.setdefault(
                            "usage", item.ctx.tags["usage"]
                        )
            agent.post_result(
                item.lease_id, item.job_id, item.epoch, item.status,
                result=item.result, error=item.error, session=session,
                op=item.op,
            )
            # Poster-thread cost as one span: finalize (incl. the deferred
            # device→host fetch) + the result post. Ships on the NEXT post
            # or the final metrics-only flush.
            agent.trace_span(
                "post", item.trace_id, item.span_parent,
                start_mono=t_fin,
                duration_s=time.perf_counter() - t_fin,
                op=item.op, status=item.status,
                finalize_ms=round(finalize_s * 1e3, 3),
            )
            # Spooled redelivery rides the poster cadence (backoff-gated
            # inside flush_spool) — the pipelined drain heals from a
            # controller blip the same way the serial loop does.
            agent.flush_spool(session=session)
            self.tasks_posted += 1
            agent.tasks_done += 1
            agent.m_tasks.inc(op=item.op, status=item.status)
            agent.recorder.record(
                "phase", phase="posted", job_id=item.job_id, op=item.op,
                lease_id=item.lease_id, status=item.status,
                duration_ms=round(duration_ms, 3),
            )
            agent.note_progress(queues={
                "staged_q": self.staged_q.qsize(),
                "post_q": self.post_q.qsize(),
            })

    # ---- lifecycle ----

    def run(self) -> None:
        # The runtime must exist before the stager reads mesh metadata, and
        # it must be built HERE: this is the device-owning thread.
        if self.agent.runtime is None:
            from agent_tpu.runtime.runtime import get_runtime

            self.agent.runtime = get_runtime(self.agent.config.device)
        log(
            "pipelined drain up", depth=self.depth,
            stage_workers=self._pool.max_workers,
            autotune=self._pool.autotune,
            double_buffer=self.double_buffer,
        )
        self._pool.start()
        self._poster.start()
        try:
            self._execute_loop()   # device work stays on the caller's thread
        finally:
            self.agent.running = False
            self._pool.join(timeout=30)
            # Graceful drain (ISSUE 10): tasks still queued for staging
            # after the workers exited are handed back (released) instead
            # of stranding the lease until the TTL; release_pending no-ops
            # unless the agent is draining.
            self._pool.release_pending()
            self._poster.join(timeout=30)
            # Final telemetry flush (metrics-only lease): the last shard's
            # finalize postdates the stager's last real poll, so without
            # this the fleet view would miss the drain's tail. A draining
            # agent's flush carries the `draining` mark — the controller
            # half of the drain handshake.
            self.agent.push_metrics()
        log("pipelined drain stopped", tasks_posted=self.tasks_posted)
