"""Result spool — a bounded redelivery queue behind ``post_result`` (ISSUE 3).

Before this, a failed ``POST /v1/results`` silently discarded a completed
TPU shard's output (logged, dropped — the reference's behavior at
``app.py:307-312``), forcing full re-execution after the lease TTL expired.
The spool keeps completed results that could not be delivered and redelivers
them with backoff on subsequent loop iterations; epoch fencing makes
redelivery safe (a result the controller already applied — or fenced — is
rejected idempotently, never applied twice).

Shape:

- **In-memory ring**, bounded at ``capacity`` — when full, the *oldest*
  entry is evicted (newer work is likelier to still be inside its lease
  window); evictions are returned to the caller so it can count the loss
  (``result_redeliveries_total{outcome="dropped_overflow"}``).
- **Optional on-disk JSONL** (``RESULT_SPOOL_PATH``): every mutation
  rewrites the file atomically (tmp + rename; the ring bound caps the
  rewrite cost), so a crashed agent's undelivered results survive restart
  and redeliver from the new incarnation. Unparseable lines (torn final
  write) are dropped at load, counted in ``load_skipped``.

The spool stores the full ``/v1/results`` wire body plus ``op`` (metric
labeling) and ``spooled_at`` (monotonic age for the optional redelivery
deadline). Delivery itself lives in ``Agent.flush_spool`` — the spool is
pure bookkeeping so it can be tested without a controller.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 512


class ResultSpool:
    """Bounded FIFO of undelivered result bodies, optionally disk-backed."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        path: Optional[str] = None,
        clock=time.monotonic,
    ) -> None:
        self.capacity = max(1, int(capacity))
        self.path = path or None
        self._clock = clock
        self._entries: "collections.deque[Dict[str, Any]]" = collections.deque()
        self.load_skipped = 0
        if self.path:
            self._load()

    # ---- persistence ----

    def _load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except ValueError:
                    self.load_skipped += 1  # torn final write from a crash
                    continue
                if isinstance(entry, dict):
                    self._entries.append(entry)
        while len(self._entries) > self.capacity:
            self._entries.popleft()
            self.load_skipped += 1

    def _persist(self) -> None:
        """Atomic rewrite — a crash mid-persist leaves the previous file, so
        at worst an already-delivered entry redelivers (fenced, harmless),
        never a lost one."""
        if not self.path:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for entry in self._entries:
                    f.write(json.dumps(entry, default=str) + "\n")
            os.replace(tmp, self.path)
        except OSError:
            # Disk trouble must not take down the drain; the in-memory ring
            # still redelivers within this incarnation.
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # ---- queue surface ----

    def put(
        self,
        lease_id: str,
        job_id: str,
        job_epoch: Any,
        status: str,
        result: Any = None,
        error: Any = None,
        op: str = "?",
    ) -> Optional[Dict[str, Any]]:
        """Spool one undelivered result. Returns the evicted entry when the
        ring was full (the caller counts it), else None."""
        entry = {
            "lease_id": lease_id,
            "job_id": job_id,
            "job_epoch": job_epoch,
            "status": status,
            "result": result,
            "error": error,
            "op": op,
            "spooled_at": self._clock(),
        }
        evicted = None
        if len(self._entries) >= self.capacity:
            evicted = self._entries.popleft()
        self._entries.append(entry)
        self._persist()
        return evicted

    def head(self) -> Optional[Dict[str, Any]]:
        return self._entries[0] if self._entries else None

    def pop_head(self) -> Optional[Dict[str, Any]]:
        if not self._entries:
            return None
        entry = self._entries.popleft()
        self._persist()
        return entry

    def age_of_head(self) -> float:
        """Seconds the oldest entry has been waiting (0 when empty)."""
        if not self._entries:
            return 0.0
        spooled = self._entries[0].get("spooled_at")
        if not isinstance(spooled, (int, float)) or isinstance(spooled, bool):
            return 0.0
        return max(0.0, self._clock() - float(spooled))

    def entries(self) -> List[Dict[str, Any]]:
        return list(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    @staticmethod
    def wire_body(entry: Dict[str, Any]) -> Dict[str, Any]:
        """The ``/v1/results`` body for a spooled entry (strips the
        bookkeeping fields)."""
        return {
            k: entry.get(k)
            for k in (
                "lease_id", "job_id", "job_epoch", "status", "result", "error"
            )
        }
