"""Device-pinned agent fleets (ISSUE 7 tentpole a).

One host, N agent processes, each owning a **disjoint slice** of the host's
accelerator devices, all leasing from one controller — the multi-process
complement of mesh mode (one agent, ``MESH_SHAPE="dp=N"``, batches sharded
across its whole mesh). The fleet is how ``n_chips > 1`` becomes real
without multi-host SPMD: the controller's fair scheduler already reads
``device_kind``/``mesh_devices``/``queue_depth`` from lease capabilities,
so shards spread across the fleet with no new protocol.

Pinning model (two fences, one grammar):

- ``CHIP_SLICE="start:count"`` — in-process: the runtime claims only that
  slice of ``jax.devices(platform)`` (``runtime.apply_chip_slice``). This is
  the only fence available on the forced-host CPU shape CI uses
  (``XLA_FLAGS=--xla_force_host_platform_device_count=K`` makes every
  process see all K virtual devices).
- ``TPU_VISIBLE_DEVICES="2,3"`` — process-level, TPU hardware only: libtpu
  hides the other chips entirely, so the runtime of agent *i* cannot touch
  a neighbor's chips even by bug. The launcher sets both; on hardware the
  in-process slice then reduces to ``0:count`` over the already-restricted
  view.

``python -m agent_tpu.agent.fleet`` is the **child** entry point: it
optionally pre-warms the op executables from ``AGENT_WARM_FILE`` (a JSON
list of ``{op, payload}`` — compile is a once-per-process cost, and a fleet
that compiles inside the timed window corrupts every scaling number), then
runs the standard agent loop (``agent/app.py``). ``scripts/fleet.py`` is
the operator CLI over :func:`spawn_fleet`.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from agent_tpu.utils.logging import log

# Repo/package root for child PYTHONPATH: children run `-m agent_tpu...`
# and must import the same tree the parent did, installed or not.
_PKG_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_FORCE_DEVICES_RE = re.compile(
    r"--xla_force_host_platform_device_count=\d+"
)

DEFAULT_NAME_PREFIX = "fleet"


def force_host_devices(xla_flags: str, n: int) -> str:
    """``XLA_FLAGS`` with the forced-host device count set to exactly ``n``
    (replacing any inherited value — a parent test env pinning 8 must not
    leak a different mesh size into fleet children)."""
    flags = _FORCE_DEVICES_RE.sub("", xla_flags or "").strip()
    return (f"{flags} --xla_force_host_platform_device_count={n}").strip()


def fleet_slice(index: int, devices_per_agent: int) -> str:
    """The ``CHIP_SLICE`` of fleet member ``index``: disjoint, contiguous,
    in launch order."""
    return f"{index * devices_per_agent}:{devices_per_agent}"


def agent_env(
    index: int,
    n_agents: int,
    devices_per_agent: int = 1,
    *,
    controller_url: str,
    tasks: str,
    platform: str = "cpu",
    base_env: Optional[Dict[str, str]] = None,
    name_prefix: str = DEFAULT_NAME_PREFIX,
    mesh_shape: str = "",
    warm_file: str = "",
    extra_env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """The environment for fleet member ``index`` of ``n_agents``.

    ``platform="cpu"`` is the CI/virtual shape: every child forces
    ``n_agents * devices_per_agent`` host devices and pins itself to its
    slice in-process. ``platform="tpu"`` is hardware: the child's process
    sees only its chips (``TPU_VISIBLE_DEVICES``) and the in-process slice
    becomes ``0:count`` over that restricted view. ``mesh_shape`` (e.g.
    ``"dp=4"``) rides through to ``MESH_SHAPE`` for mesh-mode members.
    """
    if index < 0 or index >= n_agents:
        raise ValueError(f"index {index} outside fleet of {n_agents}")
    if devices_per_agent < 1:
        raise ValueError("devices_per_agent must be >= 1")
    env = dict(base_env if base_env is not None else os.environ)
    env["CONTROLLER_URL"] = controller_url
    env["AGENT_NAME"] = f"{name_prefix}-{index}"
    env["TASKS"] = tasks
    env["PYTHONPATH"] = (
        _PKG_ROOT + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else _PKG_ROOT
    )
    if platform == "tpu":
        # Process-level pinning: libtpu hides every chip outside the slice,
        # so the in-process slice is the identity over the visible view.
        chips = range(
            index * devices_per_agent, (index + 1) * devices_per_agent
        )
        env["TPU_VISIBLE_DEVICES"] = ",".join(str(c) for c in chips)
        env["CHIP_SLICE"] = f"0:{devices_per_agent}"
    else:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = force_host_devices(
            env.get("XLA_FLAGS", ""), n_agents * devices_per_agent
        )
        env["CHIP_SLICE"] = fleet_slice(index, devices_per_agent)
    if mesh_shape:
        env["MESH_SHAPE"] = mesh_shape
    if warm_file:
        env["AGENT_WARM_FILE"] = warm_file
    if extra_env:
        env.update(extra_env)
    return env


class Fleet:
    """Handle on a spawned fleet: the child processes plus their names (the
    controller-side keys readiness and shard accounting use)."""

    def __init__(
        self, procs: List[subprocess.Popen], names: List[str]
    ) -> None:
        self.procs = procs
        self.names = names

    def alive(self) -> int:
        return sum(1 for p in self.procs if p.poll() is None)

    def poll_failures(self) -> List[int]:
        """Return codes of members that already exited nonzero — a dead
        member mid-drain means the scaling numbers are fiction."""
        return [
            p.returncode for p in self.procs
            if p.poll() is not None and p.returncode not in (0, None)
        ]

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful drain (SIGTERM → the agent's signal handler finishes the
        in-flight task), escalating to SIGKILL past ``timeout``."""
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + timeout
        for p in self.procs:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)


def spawn_fleet(
    n_agents: int,
    devices_per_agent: int = 1,
    *,
    controller_url: str,
    tasks: str,
    platform: str = "cpu",
    name_prefix: str = DEFAULT_NAME_PREFIX,
    mesh_shape: str = "",
    warm_file: str = "",
    extra_env: Optional[Dict[str, str]] = None,
    log_dir: Optional[str] = None,
) -> Fleet:
    """Spawn ``n_agents`` pinned agent processes leasing from
    ``controller_url``. Child stdout/stderr go to ``<log_dir>/<name>.log``
    when given (the launcher's own stdout stays readable at fleet scale),
    else they inherit the parent's."""
    procs: List[subprocess.Popen] = []
    names: List[str] = []
    for i in range(n_agents):
        env = agent_env(
            i, n_agents, devices_per_agent,
            controller_url=controller_url, tasks=tasks, platform=platform,
            name_prefix=name_prefix, mesh_shape=mesh_shape,
            warm_file=warm_file, extra_env=extra_env,
        )
        names.append(env["AGENT_NAME"])
        out: Any = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            out = open(
                os.path.join(log_dir, f"{env['AGENT_NAME']}.log"), "ab"
            )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "agent_tpu.agent.fleet"],
            env=env, stdout=out, stderr=subprocess.STDOUT if out else None,
            close_fds=True,
        ))
        if out is not None:
            out.close()  # the child holds its own fd now
    return Fleet(procs, names)


def wait_for_agents(
    agents_fn: Callable[[], Dict[str, Any]],
    names: Iterable[str],
    timeout: float = 180.0,
    fleet: Optional[Fleet] = None,
) -> bool:
    """Block until every name in ``names`` has polled the controller at
    least once (``agents_fn`` → the ``agents_summary()`` dict, in-process or
    scraped from ``GET /v1/status``). This is the warm/ready gate: work
    submitted before a member's first poll would be drained by a partial
    fleet and every scaling number would lie. Returns False on timeout or
    when a fleet member died before reporting in."""
    want = set(names)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            seen = set(agents_fn() or {})
        except Exception:  # noqa: BLE001 — controller may still be booting
            seen = set()
        if want <= seen:
            return True
        if fleet is not None and fleet.poll_failures():
            return False
        time.sleep(0.1)
    return False


# ---- child entry point (`python -m agent_tpu.agent.fleet`) ----

def warm_from_file(path: str) -> int:
    """Run each ``{op, payload}`` of the warm file once against the real
    runtime, building the executable cache before the first lease. Warm
    results never touch the controller; a warm failure is fatal (exit 3) —
    a member that would compile inside the timed window must not join the
    fleet silently."""
    from agent_tpu.config import Config
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext
    from agent_tpu.runtime.runtime import get_runtime

    with open(path, "r", encoding="utf-8") as f:
        specs = json.load(f)
    if not isinstance(specs, list):
        raise ValueError("warm file must be a JSON list of {op, payload}")
    config = Config.from_env()
    runtime = get_runtime(config.device)
    n = 0
    for spec in specs:
        op = get_op(str(spec["op"]))
        t0 = time.perf_counter()
        out = op(
            dict(spec.get("payload") or {}),
            OpContext(runtime=runtime, config=config),
        )
        if not (isinstance(out, dict) and out.get("ok") is True):
            raise RuntimeError(
                f"warm op {spec['op']!r} did not succeed: {str(out)[:200]}"
            )
        log(
            "fleet member warmed", op=spec["op"],
            ms=round((time.perf_counter() - t0) * 1e3, 1),
        )
        n += 1
    return n


def child_main() -> int:
    """Fleet member: warm (optional), then the standard agent loop."""
    warm_file = os.environ.get("AGENT_WARM_FILE", "")
    if warm_file:
        try:
            warm_from_file(warm_file)
        except Exception as exc:  # noqa: BLE001 — fatal by contract
            print(
                f"[agent-tpu] fleet warmup failed: "
                f"{type(exc).__name__}: {exc}",
                flush=True,
            )
            return 3
    from agent_tpu.agent.app import main as agent_main

    return agent_main()


if __name__ == "__main__":
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)  # piped-log friendliness
    sys.exit(child_main())
