"""The agent control loop (successor of reference ``app.py:143-316``).

Wire protocol (the compatibility contract, SURVEY.md §2.9):

- ``POST /v1/leases`` body ``{agent, capabilities: {ops}, max_tasks, timeout_ms,
  labels, worker_profile, metrics}``; response 204 (or empty tasks) = idle,
  else ``{lease_id, tasks: [{id|job_id, op, payload, job_epoch}]}``.
- ``POST /v1/results`` body ``{lease_id, job_id, job_epoch,
  status: "succeeded"|"failed", result, error}``; the echoed ``job_epoch`` is
  the fencing token that lets the controller discard stale retries.

Behavioral contract kept from the reference:

- Ops run **inline** on the main thread — "TPU RULE: no fork / no process
  pool" (reference ``app.py:286``). The device mesh has exactly one owner; a
  forked child would wedge the TPU runtime. Parallelism lives *inside* the op
  (batched SPMD over the mesh), not in host processes.
- status 0 = transport error (reference ``app.py:146-148``); lease errors back
  off with capped exponential backoff + decorrelated jitter (ISSUE 3 —
  ``error_backoff_sec`` is the base; the reference slept it flat) with
  per-key rate-limited logging; empty lease sleeps ``idle_sleep_sec`` ±25%
  jitter so a restarted fleet doesn't poll in lockstep.
- SIGINT/SIGTERM flip a running flag → graceful drain after the in-flight task.
- Exit code 2 when TASKS resolves to no ops.

New here: per-task phase timings (lease wait / execute / report) embedded in
the result for tracing (SURVEY.md §5.1), device telemetry from
``TpuRuntime.describe()`` shipped in the lease ``metrics`` channel alongside
host cpu/ram (reference ``app.py:74-83``), and the **result spool** (ISSUE 3):
a completed result whose post fails transiently is spooled (bounded ring +
optional ``RESULT_SPOOL_PATH`` JSONL) and redelivered with backoff on later
loop iterations instead of dropped — a controller restart inside the lease
window no longer re-executes finished shards; epoch fencing makes the
redelivery idempotent.
"""

from __future__ import annotations

import os
import signal
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from agent_tpu.agent.spool import ResultSpool
from agent_tpu.config import Config
from agent_tpu.data import wire
from agent_tpu.obs.health import RollingWindow, resolve_peak_flops
from agent_tpu.obs.metrics import MetricsRegistry
from agent_tpu.obs.profile import device_memory_stats
from agent_tpu.obs.recorder import FlightRecorder, default_dump_path
from agent_tpu.obs.usage import stamp_usage
from agent_tpu.obs.trace import (
    SpanBuffer,
    TraceContext,
    make_span,
    new_span_id,
    use_context,
)
from agent_tpu.obs import trace as obs_trace
from agent_tpu.ops import OpFn, load_ops
from agent_tpu.utils.errors import structured_error
from agent_tpu.utils.logging import RateLimiter, log
from agent_tpu.utils.retry import (
    PERMANENT,
    RetryPolicy,
    classify_http,
    jittered,
)

# result-timings key → task_phase_seconds phase label. The ops stamp
# milliseconds into ctx.tags["timings"] (see map_classify_tpu.finalize);
# the loops turn them into histogram observations in seconds.
PHASE_KEYS = (
    ("stage_ms", "stage"),
    ("queue_ms", "queue"),
    ("device_ms", "execute"),
    ("fetch_ms", "fetch"),
    ("finalize_ms", "finalize"),
)

STATUS_TRANSPORT_ERROR = 0  # "could not reach the controller at all"

# Rolling duty-cycle window (ISSUE 8): 60s matches the "is the device busy
# RIGHT NOW" question the autoscaler asks; the cumulative busy/idle
# counters remain the long-horizon view.
DUTY_WINDOW_SEC = 60.0


def collect_host_metrics() -> Dict[str, Any]:
    """``{cpu_util: 0..1, ram_mb}`` via psutil; empty when psutil is missing
    (reference ``app.py:74-83``)."""
    try:
        import psutil  # type: ignore

        return {
            "cpu_util": psutil.cpu_percent(interval=None) / 100.0,
            "ram_mb": int(psutil.virtual_memory().used / (1024 * 1024)),
        }
    except Exception:  # noqa: BLE001 — psutil optional
        return {}


class Agent:
    """One agent process: leases tasks, executes them on the mesh, reports.

    ``session`` is any object with ``post(url, json=, timeout=) -> response``
    (a ``requests.Session`` in production, a stub in tests). ``runtime`` is the
    ``TpuRuntime`` handed to ops via ``OpContext``; left None it is built
    lazily by the first op that needs the device, so pure-host agents never
    touch jax.
    """

    def __init__(
        self,
        config: Optional[Config] = None,
        session: Any = None,
        runtime: Any = None,
        registry: Any = None,
        recorder: Any = None,
        tracer: Any = None,
    ) -> None:
        self.config = config or Config.from_env()
        if session is None:
            import requests

            session = requests.Session()
        self.session = session
        self.runtime = runtime
        self.running = True
        # Graceful retirement (ISSUE 10): set by request_drain (SIGTERM,
        # autoscaler scale-down, spot reclaim). A draining agent stops
        # leasing new work, finishes the in-flight task, RELEASES the
        # unstarted remainder of its lease (status="released" — instant
        # requeue, no TTL wait, no attempt burned), flushes its spool and
        # final metrics (the flush poll carries draining=true so
        # /v1/status marks it), then exits clean.
        self.draining = False
        self.rate = RateLimiter(self.config.agent.error_log_every_sec)
        # Observability (ISSUE 2): an OWN registry/recorder per agent — the
        # controller often shares the process (tests, bench) and the fleet
        # merge must not double-count series. The snapshot ships to the
        # controller inside every lease's ``metrics`` dict.
        self.obs: MetricsRegistry = (
            registry if registry is not None else MetricsRegistry()
        )
        self.recorder: FlightRecorder = (
            recorder if recorder is not None else FlightRecorder()
        )
        # Distributed tracing (ISSUE 5): agent-side spans (stage/queue/
        # execute/post, xla.compile, spool redeliveries) buffer here and
        # piggyback onto /v1/results bodies and the lease metrics channel;
        # the controller assembles them into per-job trees. Bounded ring;
        # TRACE_ENABLED=0 makes every add a no-op.
        self.tracer: SpanBuffer = (
            tracer if tracer is not None else SpanBuffer()
        )
        self.m_tasks = self.obs.counter(
            "tasks_total", "Tasks completed by op and status",
            ("op", "status"))
        self.m_phase = self.obs.histogram(
            "task_phase_seconds",
            "Per-task phase latency (stage/queue/execute/fetch/finalize)",
            ("op", "phase"))
        self.m_lease = self.obs.counter(
            "lease_requests_total", "Lease polls by outcome", ("outcome",))
        self.m_queue = self.obs.gauge(
            "queue_depth", "Pipeline queue occupancy (staged/post)",
            ("queue",))
        self.m_device_idle = self.obs.counter(
            "device_idle_seconds_total",
            "Device-thread seconds blocked waiting for staged work")
        # Serving (ISSUE 15): live occupancy of the continuous-batching
        # decode engine's running batch — the "is iteration-level batching
        # actually batching" signal swarmtop's serving row shows.
        self.m_serve_occupancy = self.obs.gauge(
            "serve_batch_occupancy",
            "Continuous-batching running batch: requests currently seated "
            "(0 when no serving work is in flight)")
        # Per-op device attribution (ISSUE 8): busy seconds carry the op so
        # /v1/health can say WHICH workload owns the device, not just that
        # it is busy. Fleet-merge/scrape consumers that sum the family are
        # unaffected (labels sum); value() readers must now pass op=.
        self.m_device_busy = self.obs.counter(
            "device_busy_seconds_total",
            "Device-thread seconds dispatching op execute phases, per op",
            ("op",))
        self.m_duty = self.obs.gauge(
            "device_duty_cycle",
            "Rolling duty cycle: device-busy seconds inside the last "
            f"{int(DUTY_WINDOW_SEC)}s window / window span")
        self.m_flops = self.obs.counter(
            "device_flops_total",
            "Analytic model FLOPs dispatched, per op and shape bucket "
            "(matmul terms only — the ops' own estimate)",
            ("op", "shape"))
        self.m_mfu = self.obs.gauge(
            "device_mfu",
            "Model FLOPs utilization per op: analytic FLOPs / device-busy "
            "seconds / peak dense-bf16 FLOP/s (absent when the peak is "
            "unknown — PEAK_TFLOPS overrides)", ("op",))
        self.m_hbm = self.obs.gauge(
            "device_hbm_bytes",
            "Per-device accelerator memory from memory_stats(), across ALL "
            "local devices (absent on backends that report none — CPU)",
            ("device", "kind"))
        self.m_failover = self.obs.counter(
            "controller_failovers_total",
            "Active-controller rotations after transport errors "
            "(CONTROLLER_URLS failover list)")
        self.m_post_fail = self.obs.counter(
            "result_post_failures_total",
            "Result posts that failed (then spooled, or dropped if the "
            "failure was permanent)", ("op",))
        self.m_redeliveries = self.obs.counter(
            "result_redeliveries_total",
            "Spooled-result redelivery outcomes (delivered/"
            "dropped_permanent/dropped_overflow/expired)", ("outcome",))
        self.m_spool_depth = self.obs.gauge(
            "result_spool_depth", "Completed results awaiting redelivery")
        # Fault tolerance (ISSUE 3): undelivered results spool here and
        # redeliver with decorrelated backoff; lease errors share the same
        # policy. error_backoff_sec stays the lease-retry base so the legacy
        # knob keeps meaning what it meant.
        a = self.config.agent
        self.spool = ResultSpool(
            capacity=a.result_spool_max, path=a.result_spool_path or None
        )
        self._retry_policy = RetryPolicy(
            base_sec=a.retry_base_sec, max_sec=a.retry_max_sec
        )
        self._lease_retry = RetryPolicy(
            base_sec=a.error_backoff_sec, max_sec=a.retry_max_sec
        ).start()
        self._spool_retry = self._retry_policy.start()
        self._spool_next_try = 0.0
        self.m_spool_depth.set(len(self.spool))  # disk-loaded backlog
        # Periodic progress-summary state (the per-task "task done" line is
        # rate-limited away: one line per task floods stdout at drain scale).
        self._progress = {"t": time.monotonic(), "n": 0}
        # Multi-host: join the coordination service BEFORE anything touches a
        # jax backend (sizing probes jax.devices()); jax.distributed must be
        # first or it refuses and the slice desyncs.
        self.dist = self._dist_info()
        # Resolve the full op table at startup — unknown/disabled names fail
        # fast here, not mid-lease (the intended design the reference's dead
        # ops_loader.py:8-19 sketched).
        self.handlers: Dict[str, OpFn] = load_ops(list(self.config.agent.tasks))
        self._profile: Optional[Dict[str, Any]] = None
        self.tasks_done = 0
        # Live staged-queue depth source (set by PipelineRunner); the serial
        # loop has no staging queue, so it falls back to the obs gauge
        # (which is 0 unless a pipeline ever ran). Shipped in the lease
        # ``capabilities`` so the controller's scheduler can steer bulk work
        # away from backed-up agents and shrink grants (ISSUE 4).
        self.staged_depth_fn: Optional[Any] = None
        # Binary shard wire (ISSUE 6): the format the controller negotiated
        # on the last granted lease (``wire: "b1"`` in the response body),
        # None against a JSON-only controller. Read at op-context build time
        # so finalize knows whether to emit binary result columns.
        self.wire_format: Optional[str] = None
        # Staging-pool grant ask (data/staging.py): when set, lease polls
        # request max(MAX_TASKS, hint) tasks so N stage workers have work in
        # flight; the controller's grant stays advisory downward.
        self.lease_batch_hint: Optional[int] = None
        # Poster-thread session override (PipelineRunner._post_loop):
        # callable returning a session; None = a fresh requests.Session.
        self.post_session_factory: Optional[Any] = None
        # Fleet health (ISSUE 8): rolling duty window + cumulative per-op
        # busy/FLOPs for the MFU gauge. Touched only by the device-dispatch
        # thread (serial loop or the pipeline's execute loop).
        self._duty = RollingWindow(DUTY_WINDOW_SEC)
        self._busy_by_op: Dict[str, float] = {}
        self._flops_by_op: Dict[str, float] = {}
        self._peak_flops: Optional[float] = None
        # SLO page alerts piggybacked on granted leases: objectives whose
        # page episode this agent already dumped its ring for (one dump per
        # episode; clearing re-arms).
        self._page_dumped: set = set()
        self.slo_dump_paths: List[str] = []
        # On-demand deep captures (ISSUE 9): requests arrive as
        # `profile_capture` lease alerts, wrap the next matching op
        # execution in jax.profiler.trace, and the completion records ship
        # back on the lease metrics channel. Touched only by the dispatch
        # thread (captures) and the lease loop (completions).
        self._pending_captures: List[Dict[str, Any]] = []
        self._captures_seen: set = set()
        self._capture_done: List[Dict[str, Any]] = []
        # Mesh width for chip-seconds attribution: device_s × chips is what
        # the ledger turns into chip-seconds (a dp=8 dispatch second spans
        # 8 chips). Cached on first use; 1 without a runtime.
        self._usage_chips: Optional[float] = None
        # Controller failover list (ISSUE 14): CONTROLLER_URLS candidates,
        # primary first. A transport error rotates the active index
        # (sticky on success), so spool redelivery and the lease loop
        # follow a promoted hot standby without restarting the agent.
        # Index updates race benignly across the lease/poster threads.
        urls = list(a.controller_urls) or [a.controller_url]
        if a.controller_url not in urls:
            urls.insert(0, a.controller_url)
        self._controller_urls = urls
        self._url_index = 0
        # Partitioned control plane (ISSUE 18): with an explicit partition
        # map the agent wraps its session in the in-process router shim —
        # home-first leases, depth-based stealing, tagged lease ids — and
        # the whole loop above this line stays topology-blind. The spool
        # stores the TAGGED lease id, so redelivery follows the stolen
        # job's applying partition through the shim with no new spool
        # machinery. (With a router URL in CONTROLLER_URLS the router does
        # all of this server-side and this branch never runs.)
        if a.controller_partition_map:
            from agent_tpu.controller.partition import (
                PartitionMap,
                PartitionSession,
            )
            from agent_tpu.sched.steal import StealPolicy

            pmap = PartitionMap.parse(a.controller_partition_map)
            steal = StealPolicy.from_env()
            self.session = PartitionSession(
                self.session, pmap, steal=steal,
                timeout_sec=a.http_timeout_sec,
            )
            # The pipelined poster thread builds its own session
            # (requests.Session is not thread-safe) — give it the same
            # shim, or results would bypass the partition map entirely.
            if getattr(self, "post_session_factory", None) is None:
                def _partition_post_session() -> PartitionSession:
                    import requests

                    return PartitionSession(
                        requests.Session(), pmap, steal=steal,
                        timeout_sec=a.http_timeout_sec,
                    )

                self.post_session_factory = _partition_post_session

    # ---- controller I/O ----

    def active_controller_url(self) -> str:
        """The controller currently targeted — rotates through the
        CONTROLLER_URLS failover list on transport errors (ISSUE 14)."""
        urls = self._controller_urls
        return urls[self._url_index % len(urls)]

    def _note_transport_error(self, url: str) -> None:
        """Rotate to the next failover candidate. Only meaningful with
        ≥ 2 URLs; self-correcting — if the next candidate is also down,
        the following error rotates again, and a success pins the index
        wherever it landed."""
        urls = self._controller_urls
        if len(urls) < 2:
            return
        # Another thread may have rotated already; only advance past the
        # URL that actually failed so concurrent errors rotate once.
        if urls[self._url_index % len(urls)] == url:
            self._url_index = (self._url_index + 1) % len(urls)
            self.m_failover.inc()
            self.recorder.record(
                "controller_failover", failed=url,
                active=urls[self._url_index],
            )
            log(
                "controller unreachable — failing over",
                failed=url, active=urls[self._url_index],
            )

    def _post_json(
        self, path: str, body: Dict[str, Any], session: Any = None
    ) -> Tuple[int, Any]:
        """POST JSON → (status, parsed body). Status 0 = transport error; JSON
        parse falls back to raw text (reference ``app.py:143-158``).
        ``session`` overrides the agent's session — the pipelined poster
        thread brings its own (requests.Session is not thread-safe)."""
        base = self.active_controller_url()
        url = f"{base}{path}"
        try:
            resp = (session or self.session).post(
                url, json=body, timeout=self.config.agent.http_timeout_sec
            )
        except Exception as exc:  # noqa: BLE001 — any transport failure
            # Failover (ISSUE 14): the retry/spool machinery redelivers —
            # to the NEXT candidate once the list rotates.
            self._note_transport_error(base)
            return STATUS_TRANSPORT_ERROR, repr(exc)
        if resp.status_code == 204:
            return 204, None
        try:
            return resp.status_code, resp.json()
        except ValueError:
            return resp.status_code, getattr(resp, "text", None)

    def worker_profile(self) -> Dict[str, Any]:
        """Dynamic profile, built once per process (probing is not free)."""
        if self._profile is None:
            from agent_tpu.sizing import build_worker_profile

            self._profile = build_worker_profile(self.config)
        return self._profile

    def _staged_depth(self) -> int:
        if self.staged_depth_fn is not None:
            try:
                return max(0, int(self.staged_depth_fn()))
            except Exception:  # noqa: BLE001 — telemetry must never kill a lease
                return 0
        try:
            return max(0, int(self.m_queue.value(queue="staged")))
        except Exception:  # noqa: BLE001
            return 0

    def capabilities(self) -> Dict[str, Any]:
        """The lease ``capabilities`` body: ops plus the scheduler-facing
        enrichment (ISSUE 4) — ``device_kind``/``mesh_devices`` from
        ``TpuRuntime.describe()`` and the current staged ``queue_depth``.
        Shipped regardless of the controller's SCHED_POLICY (fifo ignores
        it; fair uses it for placement and grant sizing). A runtime that
        hasn't been built yet is NOT forced into existence here — pure-host
        agents never touch jax, so the device fields are simply absent."""
        caps: Dict[str, Any] = {
            "ops": sorted(self.handlers),
            "queue_depth": self._staged_depth(),
        }
        if self.config.agent.wire_binary:
            # Binary shard wire offer (ISSUE 6): a capable controller
            # answers with ``wire: "b1"``; a legacy one ignores the key and
            # the whole exchange stays plain JSON.
            caps["wire_formats"] = list(wire.FORMATS)
        if self.config.device.chip_slice:
            # Device-pinned fleet member (ISSUE 7): which slice of the
            # host's chips this agent owns. Informational for operators and
            # the fleet view; placement keeps reading device_kind/
            # mesh_devices/queue_depth.
            caps["chip_slice"] = self.config.device.chip_slice
        if self.runtime is not None:
            try:
                desc = self.runtime.describe()
                caps["device_kind"] = desc.get("platform")
                caps["mesh_devices"] = desc.get("n_devices")
            except Exception:  # noqa: BLE001 — telemetry must never kill a lease
                pass
        return caps

    def note_device_time(
        self, op: str, seconds: float, tags: Optional[Dict[str, Any]] = None
    ) -> None:
        """Per-op device attribution (ISSUE 8), called by the dispatch loop
        after every op execute: busy counter (op-labeled), rolling duty
        cycle, and — when the op stamped its analytic FLOPs into
        ``ctx.tags["device_attr"]`` — the FLOPs counter per shape bucket
        and the ``device_mfu{op}`` gauge (FLOPs / busy / peak)."""
        if seconds < 0:
            seconds = 0.0
        self.m_device_busy.inc(seconds, op=op)
        self._duty.add(seconds)
        self.m_duty.set(round(self._duty.fraction(), 4))
        self._busy_by_op[op] = self._busy_by_op.get(op, 0.0) + seconds
        task_flops = 0.0
        attr = (tags or {}).get("device_attr")
        if isinstance(attr, dict):
            flops = attr.get("flops")
            if isinstance(flops, (int, float)) and flops > 0:
                task_flops = float(flops)
                self.m_flops.inc(
                    float(flops), op=op, shape=str(attr.get("shape", "?"))
                )
                self._flops_by_op[op] = (
                    self._flops_by_op.get(op, 0.0) + float(flops)
                )
        # Per-task usage stamp (ISSUE 9): the SAME seconds that feed the
        # busy counter ride the result body, so the controller's showback
        # ledger reconciles with device_busy_seconds_total exactly.
        if self._usage_chips is None:
            try:
                self._usage_chips = (
                    float(self.runtime.n_devices)
                    if self.runtime is not None else 1.0
                )
            except Exception:  # noqa: BLE001 — telemetry must never raise
                self._usage_chips = 1.0
        stamp_usage(
            tags, device_s=seconds, chips=self._usage_chips,
            flops=task_flops or None,
        )
        if self._peak_flops is None:
            self._peak_flops = resolve_peak_flops(self.runtime)
        busy = self._busy_by_op.get(op, 0.0)
        flops_total = self._flops_by_op.get(op, 0.0)
        if self._peak_flops and busy > 0 and flops_total > 0:
            self.m_mfu.set(
                round(flops_total / busy / self._peak_flops, 6), op=op
            )

    def note_alerts(self, alerts: Any) -> None:
        """React to SLO page alerts piggybacked on a granted lease (ISSUE 8
        satellite): entering ``page`` dumps THIS agent's flight-recorder
        ring, tagged with the breaching objective's ``{tier, op}`` — the
        agent half of the evidence pair (the controller dumps its own ring
        at the transition). One dump per objective per page episode; an
        objective that recovers re-arms."""
        active: set = set()
        for a in alerts or []:
            if not isinstance(a, dict):
                continue
            if a.get("kind") == "profile_capture":
                # On-demand deep capture (ISSUE 9): arm one jax.profiler
                # trace around the next matching op execution. Deduped by
                # capture id — the alerts channel may redeliver.
                cid = a.get("capture_id")
                if isinstance(cid, str) and cid \
                        and cid not in self._captures_seen:
                    self._captures_seen.add(cid)
                    self._pending_captures.append({
                        "capture_id": cid,
                        "op": a.get("op"),
                        "duration_ms": a.get("duration_ms"),
                    })
                continue
            if a.get("state") != "page":
                continue
            objective = a.get("objective")
            if not objective:
                continue
            active.add(objective)
            if objective in self._page_dumped:
                continue
            self._page_dumped.add(objective)
            bits = "-".join(
                f"{k}{a[k]}" for k in ("tier", "tenant", "op") if a.get(k)
            ) or "all"
            path = default_dump_path(
                f"agent-{self.config.agent.agent_name}-slo-{objective}-{bits}"
            )
            self.recorder.record(
                "slo_page", objective=objective, path=path,
                **{k: a[k] for k in ("tier", "tenant", "op") if a.get(k)},
            )
            try:
                n = self.recorder.dump(path)
                self.slo_dump_paths.append(path)
                log("slo page — agent flight recorder dumped",
                    objective=objective, path=path, events=n)
            except OSError:
                pass  # a failing dump must not stop the drain
        self._page_dumped &= active

    def _refresh_hbm_gauges(self) -> None:
        """``device_hbm_bytes{device,kind}`` from ``memory_stats()`` across
        ALL local devices (ISSUE 9) — refreshed at snapshot time like the
        duty gauge. Backends without stats (CPU) export nothing: the family
        is cleanly absent, never zero-filled."""
        if self.runtime is None:
            return
        try:
            for entry in device_memory_stats(self.runtime.devices):
                for kind in ("used", "limit", "peak"):
                    if kind in entry:
                        self.m_hbm.set(
                            entry[kind], device=entry["device"], kind=kind
                        )
        except Exception:  # noqa: BLE001 — telemetry must never kill a lease
            pass

    def _metrics(self) -> Dict[str, Any]:
        m = collect_host_metrics()
        # Duty decays while idle: refresh at snapshot time so a quiet agent
        # reads 0, not its last busy moment.
        self.m_duty.set(round(self._duty.fraction(), 4))
        self._refresh_hbm_gauges()
        if self.runtime is not None:
            try:
                m["device"] = self.runtime.describe()
            except Exception:  # noqa: BLE001 — telemetry must never kill a lease
                pass
        try:
            # The fleet channel: the controller keys this snapshot by agent
            # id and merges the fleet into GET /v1/metrics.
            m["obs"] = self.obs.snapshot()
        except Exception:  # noqa: BLE001 — telemetry must never kill a lease
            pass
        return m

    def push_metrics(self, session: Any = None) -> bool:
        """Metrics-only lease poll (``max_tasks=0`` — the controller records
        telemetry and leases nothing). Drain loops call this after the last
        result posts so the final counters reach the fleet view; best-effort
        by contract."""
        spans: List[Dict[str, Any]] = []
        captures: List[Dict[str, Any]] = []
        try:
            a = self.config.agent
            metrics = self._metrics()
            spans = self._drain_spans()
            if spans:
                # Final span ship (ISSUE 5): the drain-tail spans (last
                # post/redeliver) postdate the last result post, so the
                # flush lease is what completes the last jobs' trees.
                metrics["spans"] = spans
            captures = self._drain_capture_results()
            if captures:
                metrics["profile_captures"] = captures
            body: Dict[str, Any] = {
                "agent": a.agent_name,
                # queue_depth sampled at request-BUILD time (ISSUE 6
                # satellite): the flush postdates the last real poll, so
                # without this the advertised depth would lag reality by
                # a whole poll cycle on every channel but the lease.
                "capabilities": {
                    "ops": [],
                    "queue_depth": self._staged_depth(),
                },
                "max_tasks": 0,
                "labels": a.labels,
                "metrics": metrics,
            }
            if self.draining:
                # Drain handshake (ISSUE 10): the final flush is what tells
                # the controller this member is retiring — /v1/status and
                # /v1/health mark it `draining`. Absent otherwise, keeping
                # the steady-state wire byte-identical.
                body["draining"] = True
            status, _ = self._post_json("/v1/leases", body, session=session)
            if status not in (200, 204):
                if spans:
                    self.tracer.requeue(spans)
                self._requeue_capture_results(captures)
            return status in (200, 204)
        except Exception:  # noqa: BLE001 — flush must never fail a drain
            if spans:
                self.tracer.requeue(spans)
            self._requeue_capture_results(captures)
            return False

    def record_phase_timings(
        self, op: str, timings: Optional[Dict[str, Any]],
        keys: Optional[Tuple[str, ...]] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """ctx.tags["timings"] (milliseconds) → ``task_phase_seconds``
        observations. ``keys`` restricts which timing keys count — the
        pipelined runner measures stage/execute/finalize wall-clock itself
        and only takes queue/fetch from the op timings (observing both would
        double-count). ``trace_id`` (the job id) rides along as an
        OpenMetrics exemplar, linking the histogram bucket to the trace
        that produced the sample (ISSUE 5)."""
        exemplar = (
            {"trace_id": trace_id}
            if trace_id and obs_trace.enabled() else None
        )
        for key, phase in PHASE_KEYS:
            if keys is not None and key not in keys:
                continue
            v = (timings or {}).get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.m_phase.observe(
                    float(v) / 1000.0, exemplar=exemplar, op=op, phase=phase
                )

    # ---- distributed tracing (ISSUE 5) ----

    @staticmethod
    def task_trace(task: Any) -> Tuple[Optional[str], Optional[str]]:
        """``(trace_id, parent_span_id)`` from the controller-stamped task
        trace context; ``(None, None)`` for legacy tasks or a tracing-off
        controller (agent spans are then skipped entirely)."""
        if isinstance(task, dict) and isinstance(task.get("trace"), dict):
            t = task["trace"]
            tid, sid = t.get("trace_id"), t.get("span_id")
            if isinstance(tid, str) and tid:
                return tid, sid if isinstance(sid, str) and sid else None
        return None, None

    def _process_name(self) -> str:
        return f"agent:{self.config.agent.agent_name}"

    def trace_span(
        self,
        name: str,
        trace_id: Optional[str],
        parent_span_id: Optional[str],
        start_mono: float,
        duration_s: float,
        span_id: Optional[str] = None,
        **attributes: Any,
    ) -> None:
        """Buffer one closed agent-side span; no-op without a trace id or
        with tracing disabled (the SpanBuffer short-circuits too)."""
        if not trace_id or not obs_trace.enabled():
            return
        self.tracer.add(make_span(
            name, trace_id, parent_span_id,
            start_mono=start_mono, duration_s=duration_s, span_id=span_id,
            process=self._process_name(),
            attributes={k: v for k, v in attributes.items() if v is not None},
        ))

    def _drain_spans(self) -> List[Dict[str, Any]]:
        """Pending spans for a piggyback ship ([] when tracing is off —
        nothing accumulates then either)."""
        return self.tracer.drain()

    def note_progress(self, queues: Optional[Dict[str, int]] = None) -> None:
        """Periodic progress summary (tasks/sec over the window, queue
        depths), rate-limited on the shared ``RateLimiter`` — the drain-scale
        replacement for one log line per task."""
        if not self.rate.ready("progress"):
            return
        now = time.monotonic()
        dt = now - self._progress["t"]
        dn = self.tasks_done - self._progress["n"]
        self._progress = {"t": now, "n": self.tasks_done}
        fields: Dict[str, Any] = {"tasks_done": self.tasks_done}
        if dt > 0:
            fields["tasks_per_sec"] = round(dn / dt, 3)
        if queues:
            fields.update(queues)
        log("progress", **fields)

    def lease_once(self) -> Optional[Tuple[str, List[Dict[str, Any]]]]:
        """One ``/v1/leases`` round-trip → ``(lease_id, tasks)`` or None when
        idle. Raises RuntimeError on transport/protocol errors so the caller
        applies backoff (reference ``app.py:161-195``)."""
        a = self.config.agent
        metrics = self._metrics()
        spans = self._drain_spans()
        if spans:
            # Spans piggyback on the lease metrics channel (keyed by agent
            # like the obs snapshot); undelivered batches requeue below.
            metrics["spans"] = spans
        captures = self._drain_capture_results()
        if captures:
            # Deep-capture completions ride the same channel (ISSUE 9).
            metrics["profile_captures"] = captures
        # Staging-pool grant ask: never below the configured MAX_TASKS, and
        # absent a pool hint exactly MAX_TASKS (the legacy wire).
        hint = self.lease_batch_hint
        max_tasks = (
            a.max_tasks if hint is None else max(a.max_tasks, int(hint))
        )
        status, body = self._post_json(
            "/v1/leases",
            {
                "agent": a.agent_name,
                "capabilities": self.capabilities(),
                "max_tasks": max_tasks,
                "timeout_ms": a.lease_timeout_ms,
                "labels": a.labels,
                "worker_profile": self.worker_profile(),
                "metrics": metrics,
            },
        )
        if status not in (200, 204):
            if spans:
                self.tracer.requeue(spans)
            self._requeue_capture_results(captures)
        if status == STATUS_TRANSPORT_ERROR:
            self.m_lease.inc(outcome="error")
            raise RuntimeError(f"lease transport error: {body}")
        if status == 204:
            self.m_lease.inc(outcome="idle")
            return None
        if status != 200 or not isinstance(body, dict):
            self.m_lease.inc(outcome="error")
            raise RuntimeError(f"lease HTTP {status}: {str(body)[:200]}")
        tasks = body.get("tasks")
        lease_id = body.get("lease_id")
        if not tasks:
            self.m_lease.inc(outcome="idle")
            return None
        if not isinstance(lease_id, str) or not isinstance(tasks, list):
            self.m_lease.inc(outcome="error")
            raise RuntimeError(f"malformed lease response: {str(body)[:200]}")
        # Binary-wire negotiation (ISSUE 6): the controller stamps every
        # granted lease it negotiated, so re-deriving here self-corrects if
        # the controller changed its mind (e.g. restarted without binary).
        fmt = body.get("wire")
        self.wire_format = fmt if fmt in wire.FORMATS else None
        # SLO page alerts ride granted leases (absent in steady state);
        # entering page auto-dumps this agent's flight recorder.
        self.note_alerts(body.get("alerts"))
        self.m_lease.inc(outcome="tasks")
        self.recorder.record(
            "lease", lease_id=lease_id, n_tasks=len(tasks),
            job_ids=[
                t.get("id") for t in tasks if isinstance(t, dict)
            ],
        )
        return lease_id, tasks

    def post_result(
        self,
        lease_id: str,
        job_id: str,
        job_epoch: Any,
        status: str,
        result: Any = None,
        error: Any = None,
        session: Any = None,
        op: str = "?",
    ) -> bool:
        """Post one result; on transient failure the completed result is
        SPOOLED for redelivery (never silently dropped — the reference's
        behavior this replaces, ref ``app.py:307-312``). Permanent failures
        (the controller rejected the request itself) are counted and dropped:
        resending identical bytes cannot succeed."""
        wire: Dict[str, Any] = {
            "lease_id": lease_id,
            "job_id": job_id,
            "job_epoch": job_epoch,
            "status": status,
            "result": result,
            "error": error,
        }
        spans = self._drain_spans()
        if spans:
            # Spans ride the result post (ISSUE 5) — the same piggyback the
            # metrics snapshot uses on leases. NOT stored in the spool: a
            # failed batch requeues and ships on the next post or lease.
            wire["spans"] = spans
        http_status, body = self._post_json(
            "/v1/results", wire, session=session,
        )
        if http_status in (200, 204):
            return True
        if spans:
            self.tracer.requeue(spans)
        self.m_post_fail.inc(op=op)
        failure_class = classify_http(http_status)
        self.recorder.record(
            "result_post_failed", job_id=job_id, op=op, lease_id=lease_id,
            status=http_status, **{"class": failure_class},
        )
        self.rate.log(
            "result", "post failed", status=http_status,
            failure_class=failure_class, body=str(body)[:200],
        )
        if failure_class == PERMANENT:
            return False
        evicted = self.spool.put(
            lease_id, job_id, job_epoch, status,
            result=result, error=error, op=op,
        )
        if evicted is not None:
            # Ring overflow: the OLDEST spooled result is gone for good —
            # make the loss visible (pre-spool it was every failed post).
            self.m_redeliveries.inc(outcome="dropped_overflow")
            self.recorder.record(
                "spool_overflow", job_id=evicted.get("job_id"),
                op=evicted.get("op"),
            )
        self.m_spool_depth.set(len(self.spool))
        return False

    def release_job(
        self, lease_id: str, job_id: str, job_epoch: Any, op: str = "?",
        session: Any = None,
    ) -> bool:
        """Hand one unstarted leased task back to the controller (the drain
        protocol, ISSUE 10): a ``status="released"`` result makes the job
        instantly leasable again at a bumped epoch without burning the
        attempt — scale-down never strands a lease waiting out the TTL.
        A failed post spools and redelivers like any result; if the TTL
        beats the redelivery the epoch fence discards it harmlessly."""
        self.m_tasks.inc(op=op, status="released")
        self.recorder.record(
            "task_released", job_id=job_id, op=op, lease_id=lease_id,
        )
        return self.post_result(
            lease_id, job_id, job_epoch, "released", op=op, session=session,
        )

    def release_task(
        self, lease_id: str, task: Any, session: Any = None
    ) -> bool:
        """:meth:`release_job` from a raw task dict (no payload decode —
        a release needs only the identity triple)."""
        if not isinstance(task, dict):
            return False
        job_id = task.get("id", task.get("job_id"))
        if not isinstance(job_id, str) or not job_id:
            return False
        op = task.get("op") if isinstance(task.get("op"), str) else "?"
        return self.release_job(
            lease_id, job_id, task.get("job_epoch"), op=op, session=session,
        )

    def flush_spool(self, session: Any = None, force: bool = False) -> int:
        """Redeliver spooled results, oldest first, honoring the backoff
        window between attempts (``force`` ignores it — drain shutdown).
        Stops at the first transient failure (the controller is still down);
        drops entries the controller rejects permanently or that outlived
        ``retry_deadline_sec``. Epoch fencing makes redelivery of an
        already-applied result a counted no-op, so this can never
        double-apply. Returns the number delivered."""
        if not len(self.spool):
            return 0
        now = time.monotonic()
        if not force and now < self._spool_next_try:
            return 0
        deadline = self.config.agent.retry_deadline_sec
        delivered = 0
        while len(self.spool):
            if deadline > 0 and self.spool.age_of_head() >= deadline:
                entry = self.spool.pop_head()
                self.m_redeliveries.inc(outcome="expired")
                self.recorder.record(
                    "spool_expired", job_id=(entry or {}).get("job_id"),
                    op=(entry or {}).get("op"),
                )
                continue
            entry = self.spool.head()
            t_try = time.perf_counter()
            status, _body = self._post_json(
                "/v1/results", ResultSpool.wire_body(entry), session=session
            )
            if status in (200, 204):
                self.spool.pop_head()
                delivered += 1
                self.m_redeliveries.inc(outcome="delivered")
                self.recorder.record(
                    "result_redelivered", job_id=entry.get("job_id"),
                    op=entry.get("op"),
                )
                self._trace_redelivery(entry, t_try, "delivered")
                self._spool_retry.reset()
                self._spool_next_try = 0.0
            elif classify_http(status) == PERMANENT:
                self.spool.pop_head()
                self.m_redeliveries.inc(outcome="dropped_permanent")
                self.recorder.record(
                    "spool_dropped_permanent", job_id=entry.get("job_id"),
                    op=entry.get("op"), status=status,
                )
                self._trace_redelivery(entry, t_try, "dropped_permanent")
            else:
                # Still unreachable: back off before the next redelivery
                # attempt so a down controller isn't hammered by the loop.
                self._spool_next_try = (
                    time.monotonic() + self._spool_retry.next_backoff()
                )
                break
        self.m_spool_depth.set(len(self.spool))
        return delivered

    def _trace_redelivery(
        self, entry: Dict[str, Any], t_start: float, outcome: str
    ) -> None:
        """Span for one spool redelivery attempt (ISSUE 5): parents to the
        job's lease span when the spooled result body carried the trace
        context, so a controller blip's recovery shows on the timeline."""
        job_id = entry.get("job_id")
        if not isinstance(job_id, str) or not job_id:
            return
        parent = None
        res = entry.get("result")
        if isinstance(res, dict) and isinstance(res.get("trace"), dict):
            sid = res["trace"].get("span_id")
            parent = sid if isinstance(sid, str) and sid else None
        self.trace_span(
            "result.redeliver", job_id, parent,
            start_mono=t_start,
            duration_s=time.perf_counter() - t_start,
            op=entry.get("op"), outcome=outcome,
        )

    # ---- task execution ----

    @staticmethod
    def extract_task(task: Any) -> Tuple[str, str, Dict[str, Any], Any]:
        """Task dict → ``(job_id, op, payload, job_epoch)``; accepts ``id`` or
        ``job_id``, strict types (reference ``app.py:221-234``)."""
        if not isinstance(task, dict):
            raise ValueError(f"task must be a dict, got {type(task).__name__}")
        job_id = task.get("id", task.get("job_id"))
        op = task.get("op")
        payload = task.get("payload", {})
        epoch = task.get("job_epoch")
        if not isinstance(job_id, str) or not job_id:
            raise ValueError("task missing string id/job_id")
        if not isinstance(op, str) or not op:
            raise ValueError("task missing string op")
        if payload is None:
            payload = {}
        if not isinstance(payload, dict):
            raise ValueError("task payload must be a dict")
        return job_id, op, payload, epoch

    def _op_context(self, job_id: str, lease_id: Optional[str] = None,
                    attempt: Any = None, parent_span_id: Any = None,
                    tenant: Any = None):
        from agent_tpu.runtime.context import OpContext

        # The trace triple stamped at lease time (ISSUE 2 tentpole 5): it
        # rides ctx.tags into op timings/logs and is copied into the result
        # body, so one job's life greps across controller journal, agent
        # logs, and both flight recorders. `span_id` (ISSUE 5) is the
        # controller's lease span — the parent of the agent-side spans.
        # `tenant` (ISSUE 9) rides only when the controller stamped one on
        # the task, so multi-tenant attribution greps agent-side too.
        trace = {"job_id": job_id, "attempt": attempt, "lease_id": lease_id}
        if isinstance(tenant, str) and tenant:
            trace["tenant"] = tenant
        if parent_span_id:
            trace["span_id"] = parent_span_id
        tags: Dict[str, Any] = {"job_id": job_id, "trace": trace}
        if self.wire_format:
            # Negotiated wire format (ISSUE 6): finalize reads this to emit
            # binary result columns instead of tolist()-ed JSON.
            tags["wire"] = self.wire_format
        return OpContext(
            runtime=self.runtime, config=self.config, tags=tags,
        )

    def _drain_capture_results(self) -> List[Dict[str, Any]]:
        """Completed deep-capture records awaiting their piggyback ship."""
        out, self._capture_done = self._capture_done, []
        return out

    def _requeue_capture_results(
        self, batch: List[Dict[str, Any]]
    ) -> None:
        """Undelivered completion batch goes back to the head — a capture
        completion must survive a lost lease round like spans do."""
        if batch:
            self._capture_done = batch + self._capture_done

    def _take_capture(self, op: str) -> Optional[Dict[str, Any]]:
        """Pop the first pending capture matching ``op`` (a request without
        an op matches the next task of any op)."""
        for i, cap in enumerate(self._pending_captures):
            want = cap.get("op")
            if not want or want == op:
                return self._pending_captures.pop(i)
        return None

    def _captured_call(
        self, op: str, thunk: Any, cap: Dict[str, Any]
    ) -> Any:
        """One on-demand deep capture (ISSUE 9): wrap this op execution in
        ``jax.profiler.trace`` writing into a per-capture artifact dir, and
        queue the completion record (artifact path + summary) for the next
        lease's metrics channel. A profiler that cannot start degrades to a
        plain call with an ``error`` completion — diagnostics must never
        fail the task they observe."""
        import tempfile

        record: Dict[str, Any] = {
            "capture_id": cap.get("capture_id"),
            "agent": self.config.agent.agent_name,
            "op": op,
            "status": "done",
        }
        try:
            base = os.environ.get("PROFILE_CAPTURE_DIR", "").strip()
            if base:
                artifact = os.path.join(
                    base, f"capture-{cap.get('capture_id')}"
                )
                os.makedirs(artifact, exist_ok=True)
            else:
                artifact = tempfile.mkdtemp(
                    prefix=f"agent_tpu_capture_{cap.get('capture_id')}_"
                )
            import jax

            prof = jax.profiler.trace(artifact)
            prof.__enter__()
        except Exception as exc:  # noqa: BLE001 — profiler failed to start:
            # plain call, error completion; diagnostics never fail the task.
            record.update(status="error", error=str(exc)[:300])
            self._capture_done.append(record)
            return thunk()
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(f"op:{op}"):
                return thunk()
        except Exception:
            record["status"] = "op_failed"  # trace still captured; op raised
            raise
        finally:
            try:
                prof.__exit__(None, None, None)
            except Exception:  # noqa: BLE001 — a torn trace close is not
                pass            # worth failing the op over
            dt_ms = round((time.perf_counter() - t0) * 1e3, 3)
            n_files = sum(
                len(files) for _, _, files in os.walk(artifact)
            )
            record.update(
                artifact=artifact,
                actual_duration_ms=dt_ms,
                summary={"op": op, "n_trace_files": n_files,
                         "duration_ms": dt_ms},
            )
            self._capture_done.append(record)
            self.recorder.record(
                "profile_capture", capture_id=record["capture_id"],
                op=op, artifact=artifact, status=record["status"],
            )
            log("deep capture complete", op=op, artifact=artifact,
                capture_id=record["capture_id"])

    def profiled_call(self, op: str, thunk: Any) -> Any:
        """Run ``thunk`` capturing an XProf trace for the first
        ``profile_tasks`` tasks when PROFILE_DIR is set (SURVEY.md §5.1 —
        result-embedded wall-clock timings flow regardless; traces are the
        deep-dive channel), or under an on-demand deep capture when one is
        pending for this op (ISSUE 9). Shared by the serial loop and the
        pipelined device loop so both cover phased ops too."""
        if self._pending_captures:
            cap = self._take_capture(op)
            if cap is not None:
                return self._captured_call(op, thunk, cap)
        dev = self.config.device
        if dev.profile_dir and self.tasks_done < dev.profile_tasks:
            import jax

            with jax.profiler.trace(dev.profile_dir):
                with jax.profiler.TraceAnnotation(f"op:{op}"):
                    return thunk()
        return thunk()

    def _maybe_profiled(self, op: str, fn: OpFn, payload: Dict[str, Any],
                        ctx: Any) -> Any:
        return self.profiled_call(op, lambda: fn(payload, ctx))

    def resolve_task(
        self, task: Any
    ) -> Tuple[Optional[str], str, Dict[str, Any], Any, Optional[OpFn],
               Optional[Dict[str, Any]]]:
        """Task dict → ``(job_id, op, payload, epoch, handler, error)``.

        The single definition of malformed-task salvage and the UnknownOp
        error shape, shared by the serial loop and the pipeline so the two
        paths can never drift in what they report. ``handler`` is None iff
        ``error`` is set; a malformed task with no salvageable id returns
        ``job_id=None`` (nothing to report against — drop it).
        """
        try:
            job_id, op, payload, epoch = self.extract_task(task)
            if wire.is_binary_payload(payload):
                # Binary shard wire (ISSUE 6): the controller encoded the
                # bulk columns; ops see the decoded plain payload. A
                # malformed envelope raises ValueError and reports exactly
                # like any other malformed task.
                payload = wire.decode_task_payload(payload)
        except ValueError as exc:
            self.rate.log("task:bad", "malformed task", error=str(exc))
            jid = task.get("id") if isinstance(task, dict) else None
            jid = jid if isinstance(jid, str) and jid else None
            return jid, "?", {}, None, None, structured_error(exc)
        fn = self.handlers.get(op)
        if fn is None:
            return job_id, op, payload, epoch, None, {
                "type": "UnknownOp",
                "message": f"op {op!r} not in capabilities {sorted(self.handlers)}",
                "trace": "",
            }
        return job_id, op, payload, epoch, fn, None

    def run_task(self, lease_id: str, task: Any) -> None:
        """Execute one leased task inline and report its result.

        Any raised exception becomes a ``failed`` result with the structured
        ``{type, message, trace}`` error (reference ``app.py:288-294``); a
        single-host agent never dies on an op error. Multi-host slices fail
        in lockstep instead: leader and followers all re-raise (see
        ``run_follower``), because continuing past a diverged collective
        program would wedge the slice silently.
        """
        t0 = time.perf_counter()
        job_id, op, payload, epoch, fn, resolve_error = self.resolve_task(task)
        attempt = task.get("attempt") if isinstance(task, dict) else None
        trace_id, span_parent = self.task_trace(task)
        if resolve_error is not None:
            if job_id is not None:
                self.m_tasks.inc(op=op, status="failed")
                self.recorder.record(
                    "task", job_id=job_id, op=op, status="failed",
                    lease_id=lease_id, attempt=attempt,
                    error_type=resolve_error.get("type"),
                )
                self.post_result(
                    lease_id, job_id, epoch, "failed", error=resolve_error,
                    op=op,
                )
            return

        ctx = self._op_context(job_id, lease_id=lease_id, attempt=attempt,
                               parent_span_id=span_parent,
                               tenant=task.get("tenant")
                               if isinstance(task, dict) else None)
        # The execute span id is minted up front so compile spans emitted
        # INSIDE the op (executor cache misses) can parent to it.
        exec_span_id = new_span_id()
        t_exec0 = None
        try:
            # Multi-host: every host must enter the same SPMD program in
            # lockstep — the leader publishes the task before executing it
            # (no-op on a single host). SURVEY.md §7 "multi-host control".
            self._broadcast_to_followers(op, payload)
            t_exec0 = time.perf_counter()
            # Serial loop "stage": task resolution + the broadcast — the
            # host-side work before the monolithic op call.
            self.trace_span(
                "stage", trace_id, span_parent,
                start_mono=t0, duration_s=t_exec0 - t0, op=op,
            )
            stamp_usage(ctx.tags, host_s=t_exec0 - t0)
            with use_context(TraceContext(
                trace_id=trace_id or job_id,
                parent_span_id=exec_span_id,
                tracer=self.tracer,
                registry=self.obs,
                process=self._process_name(),
            )):
                result = self._maybe_profiled(op, fn, payload, ctx)
            status = "succeeded"
            error = None
        except Exception as exc:  # noqa: BLE001 — every op error → failed result
            result = None
            status = "failed"
            error = structured_error(exc)
            self.rate.log("exec", "op raised", op=op, type=type(exc).__name__)
            if self.dist.process_count > 1:
                # Multi-host, ops are collective programs: followers that hit
                # the same exception crash (run_follower); a leader that
                # caught it and moved on would re-enter the broadcast
                # collective against dead or desynced peers — a silent slice
                # hang. Post the structured failure (so the controller can
                # stick the job failed after its one retry), then die in
                # lockstep with the followers; the slice restarts clean.
                self.post_result(
                    lease_id, job_id, epoch, status, result=None, error=error,
                    op=op,
                )
                raise
        t_done = time.perf_counter()
        if t_exec0 is not None:
            self.trace_span(
                "execute", trace_id, span_parent, span_id=exec_span_id,
                start_mono=t_exec0, duration_s=t_done - t_exec0,
                op=op, status=status,
            )
            # Serial-loop device attribution (ISSUE 8): the monolithic call
            # IS the dispatch window here (the pipelined loop measures its
            # own). Previously only the pipeline recorded busy seconds.
            self.note_device_time(op, t_done - t_exec0, ctx.tags)
        duration_ms = (t_done - t0) * 1000.0
        if isinstance(result, dict):
            result.setdefault("duration_ms", duration_ms)
            if ctx.tags.get("timings"):
                result.setdefault("timings", ctx.tags["timings"])
            result.setdefault("trace", ctx.tags.get("trace"))
            if ctx.tags.get("usage"):
                # Usage block (ISSUE 9): device/host seconds, chips, FLOPs,
                # rows — what the controller's showback ledger bills.
                result.setdefault("usage", ctx.tags["usage"])
        t_post0 = time.perf_counter()
        self.post_result(
            lease_id, job_id, epoch, status, result=result, error=error, op=op
        )
        # Emitted after the post (a span cannot include its own ship); it
        # rides the NEXT post or the final metrics-only flush.
        self.trace_span(
            "post", trace_id, span_parent,
            start_mono=t_post0, duration_s=time.perf_counter() - t_post0,
            op=op, status=status,
        )
        self.tasks_done += 1
        self.m_tasks.inc(op=op, status=status)
        # Serial phases come from the op's own timings (the monolithic call
        # gives this loop no phase boundaries of its own to measure).
        self.record_phase_timings(op, ctx.tags.get("timings"),
                                  trace_id=job_id)
        self.recorder.record(
            "task", job_id=job_id, op=op, status=status, lease_id=lease_id,
            attempt=attempt, duration_ms=round(duration_ms, 3),
            error_type=(error or {}).get("type") if error else None,
        )
        self.note_progress()

    # ---- main loop ----

    def step(self) -> bool:
        """One loop iteration. Returns True if a task was executed (so callers
        and tests can drive the loop deterministically)."""
        # Redelivery rides the loop cadence: each iteration gives spooled
        # results one (backoff-gated) chance before new work leases.
        self.flush_spool()
        try:
            leased = self.lease_once()
        except RuntimeError as exc:
            self.rate.log("lease", str(exc))
            # Decorrelated jittered backoff (base = error_backoff_sec): a
            # fleet that lost its controller must not retry in lockstep.
            time.sleep(self._lease_retry.next_backoff())
            return False
        self._lease_retry.reset()
        if leased is None:
            # ±25% jitter: a fleet restarted together must not long-poll in
            # lockstep forever (ISSUE 3 satellite).
            time.sleep(jittered(self.config.agent.idle_sleep_sec))
            return False
        lease_id, tasks = leased
        for task in tasks:
            if self.running:
                self.run_task(lease_id, task)
            elif self.draining:
                # Drain (ISSUE 10): the in-flight task above finished and
                # posted; the unstarted remainder of the lease is handed
                # back instead of abandoned to the TTL.
                self.release_task(lease_id, task)
            # else: hard stop — abandoned, the lease TTL re-queues.
        return True

    # ---- multi-host (leader/follower, SURVEY.md §5.8) ----

    def _dist_info(self):
        """Process topology; import-light so pure-host agents never touch jax
        unless multi-host env vars are actually set."""
        cfg = self.config.device
        if cfg.coordinator_address is None:
            from agent_tpu.runtime.distributed import DistInfo

            return DistInfo(process_index=0, process_count=1)
        from agent_tpu.runtime.distributed import maybe_initialize

        return maybe_initialize(
            cfg.coordinator_address, cfg.num_processes, cfg.process_id
        )

    def _broadcast_to_followers(self, op: str, payload: Dict[str, Any]) -> None:
        if self.dist.process_count == 1:
            return
        from agent_tpu.runtime.distributed import broadcast_task

        broadcast_task({"op": op, "payload": payload})

    def run_follower(self) -> None:
        """Non-leader hosts: execute every task the leader broadcasts, in
        lockstep, discarding results (the leader posts them). Blocks in the
        broadcast collective between tasks; exits on the shutdown sentinel.

        Drain-mode ops (``source_uri`` payloads) require the dataset path
        readable on **every** host of the slice — a follower that fails to
        read it host-locally never enters the SPMD program the leader is
        already inside, which would wedge the whole slice in that collective.
        """
        from agent_tpu.runtime.distributed import broadcast_task, is_shutdown

        log("follower up", process=self.dist.process_index)
        while self.running:
            task = broadcast_task(None)
            if task is None or is_shutdown(task):
                break
            fn = self.handlers.get(task.get("op"))
            if fn is None:
                # The leader only broadcasts ops it resolved — so it is
                # already inside the SPMD program waiting for our devices.
                # Skipping would wedge the whole slice in that collective;
                # failing fast turns a silent hang into a visible crash.
                raise RuntimeError(
                    f"follower has no handler for broadcast op "
                    f"{task.get('op')!r}: TASKS must be identical on every "
                    f"host of a slice (have {sorted(self.handlers)})"
                )
            try:
                fn(task.get("payload") or {}, self._op_context("follower"))
            except Exception as exc:  # noqa: BLE001 — re-raised below
                # Same reasoning as the missing-handler branch: a follower
                # that raised host-locally (e.g. a drain CSV readable only on
                # host 0) never reached the SPMD program, and the leader is
                # already blocked in it spanning our devices. Log-and-continue
                # would loop us back into the *broadcast* collective — two
                # processes in different collectives, a silent slice-wide
                # hang. Crash instead: the coordination service's heartbeat
                # then tears the slice down visibly and the controller
                # re-leases the task.
                log(
                    "follower op raised — crashing to avoid a slice hang",
                    op=task.get("op"),
                    type=type(exc).__name__,
                    error=str(exc)[:200],
                )
                raise
            self.tasks_done += 1
        log("follower drained", tasks_done=self.tasks_done)

    def run(self, max_steps: Optional[int] = None) -> None:
        info = self.dist
        if info.process_count > 1 and not info.is_leader:
            self.run_follower()
            return
        if (
            max_steps is None
            and info.process_count == 1
            and self.config.agent.pipeline_depth > 0
        ):
            # Host-side double buffering: stage/post on worker threads,
            # device dispatch stays here on the owning thread. Multi-host
            # keeps the serial lockstep loop (broadcast must serialize);
            # max_steps callers (tests) drive the deterministic serial loop.
            from agent_tpu.agent.pipeline import PipelineRunner

            PipelineRunner(self, depth=self.config.agent.pipeline_depth).run()
            return
        steps = 0
        while self.running:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        # Last chance for spooled results before exit: force past the
        # backoff window — anything still undeliverable stays in the on-disk
        # spool (if configured) for the next incarnation.
        self.flush_spool(force=True)
        # Final telemetry flush: the last task's counters postdate the last
        # real lease poll, so without this the fleet view would always lag
        # one snapshot behind a finished drain.
        self.push_metrics()
        # Clean exit only: after an op exception the followers are desynced
        # or dead, and the shutdown broadcast is itself a collective —
        # entering it would recreate the silent slice hang the lockstep
        # crash exists to avoid. On the error path the exception propagates,
        # the leader dies, and the coordination heartbeat tears down the rest.
        if info.process_count > 1:
            from agent_tpu.runtime.distributed import broadcast_shutdown

            broadcast_shutdown()

    def request_drain(self, reason: str = "drain") -> None:
        """Begin graceful retirement (ISSUE 10) — the ONE drain path shared
        by the SIGTERM handler, autoscaler scale-down, and spot reclaims:
        stop leasing, finish the in-flight task, release the unstarted
        remainder of the lease, flush spool + final metrics (tagged
        ``draining``), exit clean."""
        if not self.draining:
            self.draining = True
            log("drain requested", reason=reason)
        self.running = False

    def shutdown(self, *_args: Any) -> None:
        """Signal handler (SIGINT/SIGTERM): the drain path — a SIGTERM from
        ``Fleet.stop`` or a spot reclaim retires exactly like an autoscaler
        scale-down (reference ``app.py:239-249`` only stopped the loop)."""
        self.request_drain(reason="signal")


def main(argv: Optional[List[str]] = None) -> int:
    config = Config.from_env()
    if not config.agent.tasks:
        print("[agent-tpu] no TASKS configured; refusing to start", flush=True)
        return 2
    try:
        agent = Agent(config)
    except KeyError as exc:
        # load_ops raised on an unknown/disabled op name — same startup-fail
        # semantics as an empty TASKS list.
        print(f"[agent-tpu] bad TASKS: {exc}", flush=True)
        return 2
    signal.signal(signal.SIGINT, agent.shutdown)
    signal.signal(signal.SIGTERM, agent.shutdown)
    # Flight recorder taps: SIGUSR1 dumps the ring on demand; a fatal error
    # dumps it before the process dies — a wedged drain is diagnosable after
    # the fact without re-running it under extra logging.
    from agent_tpu.obs.recorder import default_dump_path, install_sigusr1_dump

    dump_path = default_dump_path(f"agent-{config.agent.agent_name}")
    if install_sigusr1_dump(agent.recorder, dump_path):
        log("flight recorder armed", signal="SIGUSR1", path=dump_path)
    log(
        "agent up",
        agent=config.agent.agent_name,
        controller=config.agent.controller_url,
        ops=sorted(agent.handlers),
    )
    try:
        agent.run()
    except BaseException:
        try:
            n = agent.recorder.dump(dump_path)
            log("fatal error — flight recorder dumped",
                path=dump_path, events=n)
        except OSError:
            pass
        raise
    log("agent drained", tasks_done=agent.tasks_done)
    return 0


if __name__ == "__main__":
    sys.exit(main())
