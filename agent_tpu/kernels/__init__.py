"""Hand-written Pallas (Mosaic) TPU kernels for the hot ops.

The reference's only "kernel" was the opaque Edge-TPU interpreter invoke
(reference ``ops/map_classify_tpu.py:72``). Here XLA compiles almost
everything well on its own (SURVEY.md §7: "let XLA fuse — don't hand-schedule
what the compiler already does"), so this package holds only kernels where a
hand schedule beats XLA's: flash attention, which fuses the QKᵀ → mask →
softmax → ·V chain into one VMEM-resident pass and never materializes the
[Lq, Lk] score matrix in HBM.

Every kernel ships with an XLA fallback and an interpret-mode path so the CPU
test mesh exercises identical code (same-program-different-backend rule,
SURVEY.md §7).
"""

from agent_tpu.kernels.flash_attention import (
    flash_attention,
    flash_attention_trainable,
    make_flash_attention,
    make_flash_attention_trainable,
)

__all__ = [
    "flash_attention",
    "flash_attention_trainable",
    "make_flash_attention",
    "make_flash_attention_trainable",
]
