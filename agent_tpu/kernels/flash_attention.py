"""Fused (flash) attention as a Pallas TPU kernel.

One grid program computes one [block_q, d_head] query tile for one (batch,
head). The innermost grid axis walks K/V tiles sequentially (TPU grids are
sequential, innermost fastest), carrying the streaming-softmax state — running
row max ``m``, denominator ``l``, numerator ``acc`` — in VMEM scratch that
persists across that axis. The [Lq, Lk] score matrix therefore never exists in
HBM; each tile's QKᵀ → mask → exp → ·V chain runs entirely out of VMEM, with
the MXU doing both matmuls (``preferred_element_type=f32``) and the VPU the
elementwise tail. This is the schedule XLA cannot be relied on to find whole:
it will fuse the elementwise chain, but materializes scores for long
sequences.

Numerics match ``agent_tpu.models.layers.dot_product_attention`` (f32 softmax
accumulation, finite ``NEG_INF`` masking, zero output — not NaN — for
fully-masked rows) so the kernel is a drop-in ``attn_fn``. Unsupported shapes
(mask with a query dim, tile-indivisible lengths) fall back to the dense XLA
path; off-TPU the kernel runs in interpreter mode when asked, but the runtime
only selects it on real TPU (``TpuRuntime.attention_fn``).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from agent_tpu.models.layers import NEG_INF, dot_product_attention
from agent_tpu.utils.compat import shape_dtype_struct, shard_map

_LANES = 128  # VPU lane width; scratch last dims pad to this anyway

# Below this key length the XLA dense path wins END TO END. Attention-only
# microbenchmarks on v5e show the kernel ahead already at Lk=512/d_head 64
# (1.25-1.4×), but inside the full encoder the gate at 512 measured ~13%
# SLOWER at BERT-base scale: pallas_call is a fusion barrier — XLA can no
# longer fuse the projection matmuls/softmax chain around attention — and
# the [B,L,H,D]→grid layout transitions eat the kernel's margin. The win
# is real once the dense path's [Lq, Lk] score materialization dominates.
# Measured per-call ratios vs the CURRENT dense path (which stores scores
# in bf16 — that change roughly doubled dense speed and honestly shrank
# these ratios from the old f32-score era's 3.7×/50×): 1.76× at 4k,
# 2.21× at 8k, d_head 128 (driver artifact `flash_vs_dense[_8k]`,
# BENCH_r05). The kernel's bigger win at long context is MEMORY — no
# [L, L] score tensor in HBM, so batch/length scale past where dense
# OOMs. Hence the 2048 gate; trust model-level numbers over kernel
# microbenchmarks when moving it.
FLASH_MIN_KEY_LEN = 2048

# TRAINING gates lower. Serving loses at 512 because pallas_call breaks
# XLA's fusions around a forward-only pass — but the backward-dense path
# also re-materializes and re-reads the [B, H, L, L] score tensor, which
# at BERT-base train shapes (B 256, L 512) is ~1.6 GB of HBM traffic per
# layer per direction. Measured on v5e at seq 512, remat=full: flash
# 255 ex/s vs dense 246; and because the flash backward stores NO score
# tensors, it unlocks remat-free training at batch 128 — 308 ex/s,
# 45.3% MFU vs the dense+remat baseline's 246 / 36.2% (bench `train` leg).
FLASH_TRAIN_MIN_KEY_LEN = 512

# Trace-time selection tally: ``flash_attention`` decides kernel-vs-dense while
# the surrounding jit TRACES (the gate is static shape metadata), so these
# counters tick once per compiled program, not per call. bench.py diffs them
# around a warmup to *prove* which path a compiled executable contains —
# "the bench exercises the Pallas kernel" becomes an assertion, not a belief.
SELECTION_COUNTS = {"flash": 0, "dense": 0}


def selects_flash(seq_len: int, *, block: int = 512,
                  min_key_len: Optional[int] = None) -> bool:
    """Shape-only predicate: will self-attention at ``seq_len`` (Lq == Lk,
    conforming key-padding mask, default tiles) take the Pallas path?

    Mirrors the ``supported`` gate in :func:`flash_attention` — staging code
    (``ops._model_common.split_padded_chunk``) uses it to budget dense-path
    dispatch chunks without touching device state, so a ≥2048 length that the
    kernel would still reject (not tile-divisible → dense fallback) is
    correctly treated as dense there too."""
    if min_key_len is None:
        min_key_len = FLASH_MIN_KEY_LEN
    if seq_len < min_key_len:
        return False
    return seq_len % min(block, seq_len) == 0


def selects_flash_train(seq_len: int, *, batch: int, n_heads: int,
                        mesh=None, block: int = 512,
                        min_key_len: Optional[int] = None) -> bool:
    """Shape-only predicate for the TRAINING path: will
    ``make_flash_attention_trainable(mesh)`` run the Pallas kernel for a
    [batch, n_heads, seq_len, ·] self-attention?

    Combines the trainable gate (``FLASH_TRAIN_MIN_KEY_LEN``, tile
    divisibility) with the mesh wrapper's dp/tp divisibility fallback
    (``_make_mesh_wrapper``), which otherwise silently reverts to dense.
    Code that turns OFF rematerialization on the strength of "flash is
    selected" must consult this — not the ``attn_fn`` identity, which is
    the wrapper for every shape — or a wrapper-level dense fallback would
    store [L, L] score tensors with remat disabled (bench ``train`` leg)."""
    if min_key_len is None:
        min_key_len = FLASH_TRAIN_MIN_KEY_LEN
    if not selects_flash(seq_len, block=block, min_key_len=min_key_len):
        return False
    if mesh is not None and mesh.size > 1:
        shape = dict(mesh.shape)
        if not _wrapper_shardable(batch, n_heads,
                                  shape.get("dp", 1), shape.get("tp", 1)):
            return False
    return True


def _wrapper_shardable(batch: int, n_heads: int, dp: int, tp: int) -> bool:
    """THE mesh-wrapper divisibility gate — single-sourced so
    ``_make_mesh_wrapper``'s runtime fallback and ``selects_flash_train``'s
    prediction cannot diverge (a divergence would let a caller disable
    remat while the wrapper silently runs dense)."""
    return batch % dp == 0 and n_heads % tp == 0


def _tile_softmax_update(s, keep, v_ref, m_scr, l_scr, acc_scr) -> None:
    """THE streaming-softmax tile fold: update VMEM state (m, l, acc) with
    one [bq, bk] score tile. Single-sourced for every kernel in this module
    (inference, lse-emitting trainable forward, fold, T5 bias) — and
    mirrored in ``agent_tpu.parallel.ring``'s einsum fold; keep the two in
    sync on any numerics change.

    ``s`` must already be masked to ``NEG_INF`` off-``keep``; the ``* keep``
    below makes masked entries contribute exactly 0 even in an all-masked
    tile (where s == m_new == NEG_INF would make exp() == 1).
    """
    m_prev = m_scr[:, :1]                                 # [bq, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new) * keep                         # [bq, bk]
    corr = jnp.exp(m_prev - m_new)                        # [bq, 1]
    l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0, 0],          # bf16 MXU, f32 accumulate
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float, n_k: int):
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Matmuls stay in the input dtype (bf16 on TPU = full MXU rate) with f32
    # accumulation; scaling after the dot is linear-equivalent to scaling q.
    s = jax.lax.dot_general(                              # [bq, bk] on the MXU
        q_ref[0, 0], k_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    keep = mask_ref[0, 0, :][None, :] > 0                 # [1, bk]
    s = jnp.where(keep, s, NEG_INF)
    _tile_softmax_update(s, keep, v_ref, m_scr, l_scr, acc_scr)

    @pl.when(kb == n_k - 1)
    def _emit():
        # Fully-padded rows have l == 0: emit 0, not NaN.
        o_ref[0, 0] = (
            acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,      # [B, H, Lq, D]
    k: jax.Array,      # [B, H, Lk, D]
    v: jax.Array,      # [B, H, Lk, D]
    mask: jax.Array,   # [B|1, 1, 1, Lk] key-padding mask (1 = attend)
    *,
    block_q: int = 512,
    block_k: int = 512,
    min_key_len: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in ``attn_fn``: fused attention, dense-XLA fallback off-contract.

    ``interpret=None`` auto-selects interpreter mode off-TPU so the identical
    kernel is testable on the CPU mesh; pass False to require Mosaic.

    Default 512×512 tiles measured best on v5e (scores tile = 1 MB VMEM).
    Measured v5e per-call ratios vs the dense XLA path: 1.33× at 4k
    context, 1.94× at 8k, at d_head 128 — see the ``FLASH_MIN_KEY_LEN``
    note (incl. why these shrank when dense went bf16-score) and
    ``bench.py``'s ``long_ctx`` leg, which records both as driver
    artifacts (``flash_vs_dense_speedup``, ``flash_vs_dense_8k``).
    """
    from agent_tpu.models.layers import is_key_padding_mask

    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq = min(block_q, Lq)
    bk = min(block_k, Lk)
    if min_key_len is None:
        min_key_len = FLASH_MIN_KEY_LEN
    supported = (
        is_key_padding_mask(mask, B, Lk)
        and Lk >= min_key_len
        and Lq % bq == 0
        and Lk % bk == 0
    )
    SELECTION_COUNTS["flash" if supported else "dense"] += 1
    if not supported:
        return dot_product_attention(q, k, v, mask)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [B, 1, Lk]: the singleton keeps the mask block's last-two dims legal
    # under Mosaic's (8, 128)-divisible-or-full rule (1 == full dim).
    mask3d = jnp.broadcast_to(mask[:, 0, :, :], (B, 1, Lk)).astype(jnp.int32)
    n_q, n_k = Lq // bq, Lk // bk
    kernel = functools.partial(
        _flash_kernel, scale=1.0 / np.sqrt(D), n_k=n_k
    )
    grid = (B, H, n_q, n_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running max m
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running denom l
            pltpu.VMEM((bq, D), jnp.float32),        # running numerator acc
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Lq * Lk * D,
            bytes_accessed=(2 * B * H * Lq * D + 2 * B * H * Lk * D) * q.dtype.itemsize,
            transcendentals=B * H * Lq * Lk,
        ),
        interpret=interpret,
    )(q, k, v, mask3d)


def _flash_fold_kernel(q_ref, k_ref, v_ref, mask_ref,
                       m_in_ref, l_in_ref, acc_in_ref,
                       m_out_ref, l_out_ref, acc_out_ref,
                       m_scr, l_scr, acc_scr, *, scale: float, n_k: int):
    """One flash pass over a K/V block with *carried* softmax state.

    The ring-attention hop kernel: instead of zero-initializing (m, l, acc)
    like :func:`_flash_kernel`, state streams in from the previous hop and
    streams out updated — same per-tile fold math, composable across hops.
    """
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.broadcast_to(m_in_ref[0, 0], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_in_ref[0, 0], l_scr.shape)
        acc_scr[:] = acc_in_ref[0, 0]

    s = jax.lax.dot_general(
        q_ref[0, 0], k_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    keep = mask_ref[0, 0, :][None, :] > 0
    s = jnp.where(keep, s, NEG_INF)
    _tile_softmax_update(s, keep, v_ref, m_scr, l_scr, acc_scr)

    @pl.when(kb == n_k - 1)
    def _emit():
        m_out_ref[0, 0] = m_scr[:, :1]
        l_out_ref[0, 0] = l_scr[:, :1]
        acc_out_ref[0, 0] = acc_scr[:]


def flash_fold_supported(q_shape, lk: int, *, block_q: int = 512,
                         block_k: int = 512) -> bool:
    """Static-shape gate for :func:`flash_fold` (per-hop blocks are already
    short, so no min-length heuristic here — the caller chose the ring)."""
    _, _, lq, _ = q_shape
    bq = min(block_q, lq)
    bk = min(block_k, lk)
    return lq % bq == 0 and lk % bk == 0


def flash_fold(q, k, v, mask, m, l, acc, *, block_q: int = 512,
               block_k: int = 512, interpret: Optional[bool] = None,
               vma=None):
    """Fold K/V block ``k``/``v`` (key-padding ``mask`` [B, 1, 1, Lk]) into
    streaming-softmax state ``(m, l, acc)`` → updated state. The Pallas form
    of ``agent_tpu.parallel.ring``'s einsum fold — one fused VMEM pass.

    ``vma``: varying-mesh-axes annotation for the outputs — required when
    called inside a ``shard_map`` with vma checking (the ring passes its
    mesh axes); leave None outside shard_map.
    """
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq = min(block_q, Lq)
    bk = min(block_k, Lk)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    mask3d = jnp.broadcast_to(mask[:, 0, :, :], (B, 1, Lk)).astype(jnp.int32)
    n_q, n_k = Lq // bq, Lk // bk
    kernel = functools.partial(
        _flash_fold_kernel, scale=1.0 / np.sqrt(D), n_k=n_k
    )
    qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(B, H, n_q, n_k),
        in_specs=[
            qspec, kspec, kspec,
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
            sspec, sspec, qspec,
        ],
        out_specs=(sspec, sspec, qspec),
        out_shape=(
            shape_dtype_struct(m.shape, jnp.float32, vma=vma),
            shape_dtype_struct(l.shape, jnp.float32, vma=vma),
            shape_dtype_struct(acc.shape, jnp.float32, vma=vma),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, mask3d, m.astype(jnp.float32), l.astype(jnp.float32),
      acc.astype(jnp.float32))


def _flash_t5_kernel(q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref,
                     m_scr, l_scr, acc_scr, *, scale: float, n_k: int,
                     bq: int, bk: int, num_buckets: int, max_distance: int,
                     bidirectional: bool, n_heads: int):
    """Flash attention with T5's bucketed relative-position bias computed
    PER TILE in VMEM — the [H, Lq, Lk] bias tensor never exists in HBM
    (at 16 heads × 8k² it alone would be 4 GB, defeating the kernel).

    ``bias_ref`` is this head's [num_buckets, 1] learned bias column. The
    tile's bucket map comes from absolute tile offsets (grid coords × block
    sizes + iota); the gather from the 32-entry table is an unrolled
    one-hot accumulation (Mosaic has no vectorized gather; 32 masked adds
    per tile cost ~VPU parity with the tile's MXU work).
    """
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qi = pl.program_id(2)
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    # The ONE bucket definition (models/t5.py, HF semantics) traces fine
    # inside the kernel — plain jnp ops; trace-time import avoids a cycle.
    from agent_tpu.models.t5 import relative_position_bucket

    bucket = relative_position_bucket(
        k_pos - q_pos, bidirectional, num_buckets, max_distance
    )

    # The whole [num_buckets, H] table rides in VMEM (tiny; Mosaic requires
    # full-dim blocks for its shape). This head's column is selected with a
    # one-hot reduction (Mosaic lowers neither dynamic_slice nor gathers):
    # cols[b, 0] = table[b, head].
    head = pl.program_id(1)
    h_iota = jax.lax.broadcasted_iota(jnp.int32, (1, n_heads), 1)
    head_1h = (h_iota == head).astype(jnp.float32)            # [1, H]
    cols = jnp.sum(bias_ref[:, :] * head_1h, axis=1, keepdims=True)
    bias = jnp.zeros((bq, bk), dtype=jnp.float32)
    for b in range(num_buckets):  # static unroll: one-hot gather
        bias += jnp.where(bucket == b, cols[b, 0], 0.0)

    s = jax.lax.dot_general(
        q_ref[0, 0], k_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale + bias
    keep = mask_ref[0, 0, :][None, :] > 0
    s = jnp.where(keep, s, NEG_INF)
    _tile_softmax_update(s, keep, v_ref, m_scr, l_scr, acc_scr)

    @pl.when(kb == n_k - 1)
    def _emit():
        o_ref[0, 0] = (
            acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def flash_attention_t5(
    q: jax.Array,          # [B, H, Lq, D]
    k: jax.Array,          # [B, H, Lk, D]
    v: jax.Array,          # [B, H, Lk, D]
    mask: jax.Array,       # [B|1, 1, 1, Lk] key-padding mask (1 = attend)
    rel_bias: jax.Array,   # [num_buckets, H] learned bias table
    *,
    bidirectional: bool = True,
    max_distance: int = 128,
    scale: float = 1.0,    # T5 attention is unscaled
    block_q: int = 512,
    block_k: int = 512,
    min_key_len: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Fused T5-style attention (scores·scale + bucketed relative bias →
    masked streaming softmax → ·V). Returns the [B, H, Lq, D] context, or
    **None** for unsupported shapes — the caller keeps its own dense path
    (the trace-time None keeps selection visible to the model code instead
    of silently diverging here).
    """
    from agent_tpu.models.layers import is_key_padding_mask

    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    num_buckets = int(rel_bias.shape[0])
    bq = min(block_q, Lq)
    bk = min(block_k, Lk)
    if min_key_len is None:
        min_key_len = FLASH_MIN_KEY_LEN
    supported = (
        is_key_padding_mask(mask, B, Lk)
        and Lk >= min_key_len
        and Lq % bq == 0
        and Lk % bk == 0
        and rel_bias.ndim == 2
        and rel_bias.shape[1] == H
    )
    SELECTION_COUNTS["t5_flash" if supported else "t5_dense"] = (
        SELECTION_COUNTS.get("t5_flash" if supported else "t5_dense", 0) + 1
    )
    if not supported:
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    mask3d = jnp.broadcast_to(mask[:, 0, :, :], (B, 1, Lk)).astype(jnp.int32)
    n_q, n_k = Lq // bq, Lk // bk
    kernel = functools.partial(
        _flash_t5_kernel, scale=scale, n_k=n_k, bq=bq, bk=bk,
        num_buckets=num_buckets, max_distance=max_distance,
        bidirectional=bidirectional, n_heads=H,
    )
    return pl.pallas_call(
        kernel,
        grid=(B, H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
            # The whole bias table (tiny): Mosaic requires the last two
            # block dims divisible by (8, 128) OR equal to the full array
            # dims — only the latter fits [num_buckets, H].
            pl.BlockSpec((num_buckets, H), lambda b, h, i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Lq * Lk * D,
            bytes_accessed=(2 * B * H * Lq * D + 2 * B * H * Lk * D)
            * q.dtype.itemsize,
            transcendentals=B * H * Lq * Lk,
        ),
        interpret=interpret,
    )(q, k, v, mask3d, rel_bias.astype(jnp.float32))


def make_flash_attention_t5(mesh):
    """Mesh-aware T5 kernel: ``flash_attention_t5`` wrapped in ``shard_map``
    (batch over ``dp``, heads over ``tp`` — the bias table's head dim shards
    with the heads). Same rationale as :func:`make_flash_attention`:
    ``pallas_call`` has no GSPMD partitioning rule, so the bare kernel on a
    multi-chip mesh would replicate the full batch per chip. Returns a
    callable with the kernel's signature that yields **None** (dense
    fallback) for shapes the wrapper can't shard or the kernel declines.
    """
    if mesh.size == 1:
        return flash_attention_t5

    from jax.sharding import PartitionSpec as P

    shape = dict(mesh.shape)
    dp = shape.get("dp", 1)
    tp = shape.get("tp", 1)

    def wrapper(q, k, v, mask, rel_bias, *, bidirectional=True,
                max_distance=128, scale=1.0, block_q=512, block_k=512,
                min_key_len=None, interpret=None):
        from agent_tpu.models.layers import (
            is_key_padding_mask,
            materialize_key_padding_mask,
        )

        B, H, Lq, D = q.shape
        Lk = k.shape[2]
        if min_key_len is None:
            min_key_len = FLASH_MIN_KEY_LEN
        ok = (
            is_key_padding_mask(mask, B, Lk)
            and Lk >= min_key_len
            and Lq % min(block_q, Lq) == 0
            and Lk % min(block_k, Lk) == 0
            and B % dp == 0
            and H % tp == 0
            and rel_bias.shape[1] == H
        )
        SELECTION_COUNTS["t5_flash" if ok else "t5_dense"] = (
            SELECTION_COUNTS.get("t5_flash" if ok else "t5_dense", 0) + 1
        )
        if not ok:
            return None

        inner = functools.partial(
            flash_attention_t5,
            bidirectional=bidirectional, max_distance=max_distance,
            scale=scale, block_q=block_q, block_k=block_k,
            min_key_len=0,  # validated above, on the GLOBAL shapes
            interpret=interpret,
        )
        sharded = shard_map(
            inner,
            mesh=mesh,
            in_specs=(
                P("dp", "tp", None, None),
                P("dp", "tp", None, None),
                P("dp", "tp", None, None),
                P("dp", None, None, None),
                P(None, "tp"),   # bias table: head dim shards with heads
            ),
            out_specs=P("dp", "tp", None, None),
            check_vma=False,  # pallas out_shape carries no vma annotation
        )
        return sharded(
            q, k, v, materialize_key_padding_mask(mask, B, Lk), rel_bias
        )

    return wrapper


# ---------------------------------------------------------------------------
# Trainable flash attention: custom_vjp with Pallas forward AND backward.
#
# The inference kernel above is forward-only — differentiating through it
# would fail (pallas_call has no AD rule), so the training path previously
# fell back to dense attention, materializing [B, H, L, L] scores in the
# backward and capping train MFU well below serving. The trainable variant
# uses the standard recompute scheme (FlashAttention-2 backward):
#
#   forward: one extra [B, H, Lq, 1] output — the row logsumexp
#            ``lse = m + log(l)`` — saved as the only softmax residual;
#   backward: ``delta = rowsum(dO ⊙ O)`` (cheap XLA reduction), then two
#            Pallas kernels that RECOMPUTE the normalized probabilities
#            ``p = exp(s − lse)`` per tile in VMEM:
#              • dQ kernel, grid (B, H, n_q, n_k): stream K/V tiles,
#                accumulate ``dq += (p ∘ (dO·Vᵀ − delta)) · K · scale``;
#              • dK/dV kernel, grid (B, H, n_k, n_q): stream Q tiles,
#                accumulate ``dv += pᵀ·dO`` and ``dk += dsᵀ·Q · scale``.
#            The [Lq, Lk] score/probability matrices never exist in HBM in
#            either direction.
# ---------------------------------------------------------------------------


def _flash_fwd_lse_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                          m_scr, l_scr, acc_scr, *, scale: float, n_k: int):
    """:func:`_flash_kernel` + one extra output: the row logsumexp residual."""
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    s = jax.lax.dot_general(
        q_ref[0, 0], k_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    keep = mask_ref[0, 0, :][None, :] > 0
    s = jnp.where(keep, s, NEG_INF)
    _tile_softmax_update(s, keep, v_ref, m_scr, l_scr, acc_scr)

    @pl.when(kb == n_k - 1)
    def _emit():
        l_fin = l_scr[:, :1]
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l_fin, 1e-30)).astype(
            o_ref.dtype
        )
        # Fully-masked rows: m == NEG_INF, l == 0 → lse ≈ NEG_INF − 69; the
        # backward's exp(s − lse) would overflow there but is zeroed by the
        # key mask before use.
        lse_ref[0, 0] = m_scr[:, :1] + jnp.log(jnp.maximum(l_fin, 1e-30))


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                         delta_ref, dq_ref, dq_scr, *, scale: float,
                         n_k: int):
    """dQ for one query tile, streaming K/V tiles on the inner grid axis."""
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    s = jax.lax.dot_general(                                  # [bq, bk]
        q_ref[0, 0], k_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    keep = mask_ref[0, 0, :][None, :] > 0
    # Normalized probabilities, recomputed from the saved logsumexp. The
    # clamp bounds exp() for fully-masked rows (lse ≈ NEG_INF) where the
    # mask zeroes p anyway — exp(80) is finite in f32, so no inf*0.
    p = jnp.where(
        keep, jnp.exp(jnp.minimum(s - lse_ref[0, 0], 80.0)), 0.0
    )
    dp = jax.lax.dot_general(                                 # dO · Vᵀ
        do_ref[0, 0], v_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[0, 0])                           # [bq, bk] f32
    dq_scr[:] += scale * jax.lax.dot_general(
        ds.astype(k_ref.dtype), k_ref[0, 0],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )

    @pl.when(kb == n_k - 1)
    def _emit():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          scale: float, n_q: int):
    """dK and dV for one key tile, streaming Q tiles on the inner grid axis."""
    qb = pl.program_id(3)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    s = jax.lax.dot_general(                                  # [bq, bk]
        q_ref[0, 0], k_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    keep = mask_ref[0, 0, :][None, :] > 0
    p = jnp.where(
        keep, jnp.exp(jnp.minimum(s - lse_ref[0, 0], 80.0)), 0.0
    )
    # dV += pᵀ · dO — explicit .T then dot: the Mosaic-supported transposed
    # contraction (same pattern as jax.experimental.pallas.ops.tpu).
    dv_scr[:] += jax.lax.dot(
        p.T.astype(do_ref.dtype), do_ref[0, 0],
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(                                 # dO · Vᵀ
        do_ref[0, 0], v_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[0, 0])
    dk_scr[:] += scale * jax.lax.dot(
        ds.T.astype(q_ref.dtype), q_ref[0, 0],
        preferred_element_type=jnp.float32,
    )

    @pl.when(qb == n_q - 1)
    def _emit():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_fwd_res(q, k, v, mask3d, *, block_q, block_k, interpret, scale):
    """Forward pallas_call emitting (output, [B, H, Lq, 1] logsumexp)."""
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq = min(block_q, Lq)
    bk = min(block_k, Lk)
    n_q, n_k = Lq // bq, Lk // bk
    qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_flash_fwd_lse_kernel, scale=scale, n_k=n_k),
        grid=(B, H, n_q, n_k),
        in_specs=[
            qspec, kspec, kspec,
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            sspec,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, Lq, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Lq * Lk * D,
            bytes_accessed=(2 * B * H * Lq * D + 2 * B * H * Lk * D)
            * q.dtype.itemsize,
            transcendentals=B * H * Lq * Lk,
        ),
        interpret=interpret,
    )(q, k, v, mask3d)


def _flash_bwd_res(q, k, v, mask3d, o, lse, do, *, block_q, block_k,
                   interpret, scale):
    """Backward: (dq, dk, dv) via the two streaming kernels."""
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq = min(block_q, Lq)
    bk = min(block_k, Lk)
    n_q, n_k = Lq // bq, Lk // bk
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )                                                          # [B, H, Lq, 1]

    def qtile(b, h, i, j):
        return (b, h, i, 0)

    def ktile(b, h, i, j):
        return (b, h, j, 0)

    qspec = pl.BlockSpec((1, 1, bq, D), qtile, memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, 1, bk, D), ktile, memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec((1, 1, bq, 1), qtile, memory_space=pltpu.VMEM)
    mspec = pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM)
    bwd_cost = pl.CostEstimate(
        flops=10 * B * H * Lq * Lk * D,
        bytes_accessed=(4 * B * H * Lq * D + 4 * B * H * Lk * D)
        * q.dtype.itemsize,
        transcendentals=2 * B * H * Lq * Lk,
    )
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, n_k=n_k),
        grid=(B, H, n_q, n_k),
        in_specs=[qspec, kspec, kspec, mspec, qspec, sspec, sspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        cost_estimate=bwd_cost,
        interpret=interpret,
    )(q, k, v, mask3d, do, lse, delta)

    # K-tile outer, Q-tile inner: swap the roles of the last two grid axes.
    def qtile_t(b, h, j, i):
        return (b, h, i, 0)

    def ktile_t(b, h, j, i):
        return (b, h, j, 0)

    qspec_t = pl.BlockSpec((1, 1, bq, D), qtile_t, memory_space=pltpu.VMEM)
    kspec_t = pl.BlockSpec((1, 1, bk, D), ktile_t, memory_space=pltpu.VMEM)
    sspec_t = pl.BlockSpec((1, 1, bq, 1), qtile_t, memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, n_q=n_q),
        grid=(B, H, n_k, n_q),
        in_specs=[
            qspec_t, kspec_t, kspec_t,
            pl.BlockSpec((1, 1, bk), lambda b, h, j, i: (b, 0, j),
                         memory_space=pltpu.VMEM),
            qspec_t, sspec_t, sspec_t,
        ],
        out_specs=(kspec_t, kspec_t),
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        cost_estimate=bwd_cost,
        interpret=interpret,
    )(q, k, v, mask3d, do, lse, delta)
    return dq, dk, dv


def flash_attention_trainable(
    q: jax.Array,      # [B, H, Lq, D]
    k: jax.Array,      # [B, H, Lk, D]
    v: jax.Array,      # [B, H, Lk, D]
    mask: jax.Array,   # [B|1, 1, 1, Lk] key-padding mask (1 = attend)
    *,
    block_q: int = 512,
    block_k: int = 512,
    min_key_len: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Differentiable drop-in ``attn_fn``: Pallas forward AND backward.

    Same numerics and shape rules as :func:`flash_attention`, but the
    length gate defaults to ``FLASH_TRAIN_MIN_KEY_LEN`` (512, not 2048):
    in training the kernel also eliminates the backward's score-tensor HBM
    round trip, which flips the 512 verdict — see the gate note above.
    Unsupported shapes take the dense XLA path, which autodiff handles
    natively. The
    Pallas path registers a ``custom_vjp`` whose backward runs the two
    streaming kernels above — training at long context no longer
    materializes [Lq, Lk] score matrices in either pass.

    Gradient caveat: rows whose mask keeps NO keys get zero (dq, dk, dv)
    contributions here, while the dense path backpropagates through its
    uniform-softmax-then-zero guard; with any real key present the two
    paths agree to dtype tolerance (``tests/test_flash_attention.py``).
    """
    from agent_tpu.models.layers import is_key_padding_mask

    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq = min(block_q, Lq)
    bk = min(block_k, Lk)
    if min_key_len is None:
        min_key_len = FLASH_TRAIN_MIN_KEY_LEN  # training gate — see note
    supported = (
        is_key_padding_mask(mask, B, Lk)
        and Lk >= min_key_len
        and Lq % bq == 0
        and Lk % bk == 0
    )
    key = "flash_train" if supported else "dense_train"
    SELECTION_COUNTS[key] = SELECTION_COUNTS.get(key, 0) + 1
    if not supported:
        return dot_product_attention(q, k, v, mask)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = 1.0 / float(np.sqrt(D))
    mask3d = jnp.broadcast_to(mask[:, 0, :, :], (B, 1, Lk)).astype(jnp.int32)
    return _trainable_core(block_q, block_k, interpret, scale)(
        q, k, v, mask3d
    )


@functools.lru_cache(maxsize=None)
def _trainable_core(block_q: int, block_k: int, interpret: bool,
                    scale: float):
    """The custom_vjp attention for one static (tiles, interpret, scale).

    The mask rides as a PRIMAL argument (``None`` cotangent), never in a
    closure: a closed-over traced mask would leak its tracer into the
    backward trace — ``jax.checkpoint`` replays the forward under a
    different trace than the one that runs ``bwd``. The lru_cache keeps one
    function identity per static config, so jit caches see a stable callee.
    """

    @jax.custom_vjp
    def attn(q, k, v, mask3d):
        o, _ = _flash_fwd_res(q, k, v, mask3d, block_q=block_q,
                              block_k=block_k, interpret=interpret,
                              scale=scale)
        return o

    def fwd(q, k, v, mask3d):
        o, lse = _flash_fwd_res(q, k, v, mask3d, block_q=block_q,
                                block_k=block_k, interpret=interpret,
                                scale=scale)
        return o, (q, k, v, mask3d, o, lse)

    def bwd(res, do):
        q, k, v, mask3d, o, lse = res
        dq, dk, dv = _flash_bwd_res(q, k, v, mask3d, o, lse, do,
                                    block_q=block_q, block_k=block_k,
                                    interpret=interpret, scale=scale)
        return dq, dk, dv, None

    attn.defvjp(fwd, bwd)
    return attn


def _make_mesh_wrapper(mesh, inner, dense_counter_key: Optional[str]):
    """ONE shard_map wrapper for both flash kernels (batch over ``dp``,
    heads over ``tp``) — inference and trainable share the sharding layout,
    the divisibility gate, and the mask materialization, so a future spec
    change cannot silently diverge the two paths.

    ``dense_counter_key`` ticks ``SELECTION_COUNTS`` when the WRAPPER (not
    the per-shard kernel) decides on the dense fallback: inside shard_map
    the per-shard call ticks its own counter, but a wrapper-level decline
    would otherwise be invisible to the trace-time selection proof.
    """
    from jax.sharding import PartitionSpec as P

    shape = dict(mesh.shape)
    dp = shape.get("dp", 1)
    tp = shape.get("tp", 1)

    sharded = shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            P("dp", "tp", None, None),
            P("dp", "tp", None, None),
            P("dp", "tp", None, None),
            P("dp", None, None, None),
        ),
        out_specs=P("dp", "tp", None, None),
        # pallas_call's out_shape carries no varying-mesh-axes annotation, so
        # the vma checker can't see through it; the in/out specs above are the
        # full contract here.
        check_vma=False,
    )

    def mesh_attention(q, k, v, mask):
        from agent_tpu.models.layers import (
            is_key_padding_mask,
            materialize_key_padding_mask,
        )

        B, H, _, _ = q.shape
        Lk = k.shape[2]
        ok = is_key_padding_mask(mask, B, Lk) and _wrapper_shardable(
            B, H, dp, tp
        )
        if not ok:
            if dense_counter_key is not None:
                SELECTION_COUNTS[dense_counter_key] = (
                    SELECTION_COUNTS.get(dense_counter_key, 0) + 1
                )
            return dot_product_attention(q, k, v, mask)
        return sharded(q, k, v, materialize_key_padding_mask(mask, B, Lk))

    return mesh_attention


def make_flash_attention_trainable(mesh):
    """Mesh-aware trainable flash attention — :func:`make_flash_attention`
    for the training path. Batch shards over ``dp``, heads over ``tp``;
    ``shard_map`` differentiates through the per-shard ``custom_vjp``, so
    the backward kernels also run sharded. Unsupported shapes fall back to
    the dense path (GSPMD + autodiff handle it)."""
    if mesh.size == 1:
        return flash_attention_trainable
    return _make_mesh_wrapper(mesh, flash_attention_trainable, "dense_train")


def make_flash_attention(mesh):
    """Mesh-aware flash attention: the kernel wrapped in ``shard_map``.

    ``pallas_call`` has no GSPMD partitioning rule, so jitting the bare kernel
    over a dp/tp mesh silently all-gathers the batch and runs the full-batch
    kernel replicated on every chip. Wrapping in ``shard_map`` (batch over
    ``dp``, heads over ``tp``) keeps each chip on its own shard. Single-device
    meshes skip the wrapper. Shapes the wrapper can't shard (batch or heads
    indivisible) fall back to the dense XLA path, which GSPMD partitions fine.
    """
    if mesh.size == 1:
        return flash_attention
    # No counter key: the wrapper-level dense fallback predates the proof
    # discipline and tests pin the "dense" counter to per-kernel decisions.
    return _make_mesh_wrapper(mesh, flash_attention, None)
