"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline: rows/sec/chip on ``map_classify_tpu`` (the BASELINE.json north-star
metric; target ≥10,000 rows/sec/chip). Ops are measured end to end — host
tokenization, padding, device transfer, jitted forward, top-k — because that
is what a leased task pays; compile time is excluded by warmup (the executable
cache makes it a once-per-process cost, reference handle-singleton semantics).

Methodology: every throughput number is the **median of N measurement
windows** with the min→max spread recorded next to it (``spread_pct``), so a
lucky window can't inflate the trend line and a noisy one can't hide.

Legs (the ``legs`` object in the output line):

- ``flagship``     — classify at the default serving config (the r01/r02
                     trend line; BASELINE.json north star ≥10k rows/s/chip).
- ``bert_base``    — classify at the BERT-base scale BASELINE.json names
                     (d_model 768 / 12 layers / 12 heads / seq 512), with an
                     **mfu** field: achieved FLOP/s ÷ the chip's peak bf16
                     FLOP/s (looked up from device_kind, override with
                     ``BENCH_PEAK_TFLOPS``).
- ``bert_base_int8`` — the same BERT-base leg under
                     ``model_config {"quant": "int8"}`` (W8A8, models/quant.py)
                     with the speedup over bf16 and the top-1 agreement rate
                     vs bf16 on a diverse 512-row batch.
- ``long_ctx``     — classify over 4k-token documents. The warmup *proves*
                     the compiled program contains the Pallas flash kernel by
                     diffing the kernel's trace-time selection counters
                     (``kernels.flash_attention.SELECTION_COUNTS``); it also
                     records a dense-vs-flash model-level speedup ratio.
- ``summarize``    — greedy decode tokens/sec at the serving config.
- ``csv_index``    — cold CSV index build MB/s (the C++/Python scanner).
- ``drain``        — controller→HTTP→agent drain of a sharded CSV through the
                     **pipelined** runner (host-side double buffering), both
                     classify-only (comparable to the pure-op number) and
                     **mixed classify+summarize** (the BASELINE.json north-star
                     job shape at bench scale).
- ``drain_multichip`` — the swarm across N chips (ISSUE 7): a fleet of N
                     device-pinned agent subprocesses and a dp=N mesh agent
                     drain the same sharded job on the forced-host CPU smoke
                     shape, bit-identical to the 1-chip reference, with
                     ``scaling_efficiency`` = rows/sec at N ÷ N·rows/sec at 1
                     (asserted ≥ 0.8 when the host has ≥ N cores).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time

# Measurement configuration — single definitions shared by the bench
# functions and the bench_params field in the output line, so the recorded
# config can never drift from the executed one.
WINDOWS = 3
NOISY_WINDOWS = 5  # flagship + long-ctx legs (see main)
FLAGSHIP_BATCH = 8192
FLAGSHIP_ITERS = 10
# 4096-row payloads dispatch as 16 back-to-back 256-row device programs
# (ops._model_common.split_padded_chunk) — the measured v5e sweet spot for
# dense seq-512 attention — with ONE deferred fetch, so the tunneled
# host↔device round trip amortizes over the whole payload.
BERT_BATCH = 4096
BERT_ITERS = 2
BERT_CONFIG = {
    "d_model": 768, "n_heads": 12, "n_layers": 12, "d_ff": 3072,
    "max_len": 512,
}
LONG_CTX_BATCH = 128
LONG_CTX_ITERS = 5
# d_head = 128 (d_model/n_heads): the flash kernel's matmuls carry the head
# dim on the MXU contraction, so d_head < 128 underfills the systolic array —
# measured on v5e: 15 TF/s at d_head 32 vs 68 TF/s at d_head 128. Long-context
# configs in this framework keep d_head at the MXU tile width.
LONG_CTX_CONFIG = {"d_model": 512, "n_heads": 4, "max_len": 4096}
SUMMARIZE_BATCH = 256
SUMMARIZE_MAX_NEW = 32
# Quantization-fidelity sample size (rows) for the agreement numbers that
# ride next to the int8/w8a16 throughput legs. 512 rows put the one-sided
# 95% CI for "agreement ≥ 0.99" at ~±0.9 points — too loose for a headline;
# ≥5k rows tightens it below ±0.3 (round-4 ask #4).
AGREEMENT_ROWS = 5120
# Batch 128 + remat-free is the measured optimum now that the trainable
# flash kernel gates at 512 (FLASH_TRAIN_MIN_KEY_LEN): no stored score
# tensors OR block activations. Swept on v5e: 128/none 308 ex/s (45.3%
# MFU) > 256/full-remat 246 (36.2%) > 512/full 230; 256/none OOMs.
TRAIN_BATCH = 128
TRAIN_STEPS = 8
DRAIN_ROWS = 65_536
DRAIN_SHARD_SIZE = 8192
DRAIN_SUMMARIZE_ROWS = 16_384
# Multi-chip drain leg (ISSUE 7): N device-pinned agent subprocesses (and a
# dp=N mesh agent) drain the same sharded job on the forced-host CPU smoke
# shape — the scaling demonstration runs on virtual chips so the leg is
# recordable on any host; real-TPU fleets use scripts/fleet.py directly.
MULTICHIP_AGENTS = 4
MULTICHIP_ROWS = 16_384
MULTICHIP_SHARD = 512
MULTICHIP_MODEL = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}
# Near-linear bar: rows/sec at N agents ≥ 0.8 · N · rows/sec at 1 agent.
# Asserted only when the host has at least one core per agent — on fewer
# cores the fleet can only conserve throughput, and "0.25 at 4 agents on 1
# core" is the expected physics, not a regression.
MULTICHIP_SCALING_FLOOR = 0.8
# Summarize throughput scales with decode rows in flight: measured 4,980 /
# 6,588 / 7,779 / 8,093 rows/s at payload 1k/2k/4k/8k (chained ≤1024-row
# programs at the time), 9,132 as ONE B=8192 program — per-step decode
# matmuls are [B, d_model]-thin, so only batch fills the MXU (see
# ops/map_summarize.MAX_DECODE_ROWS).
DRAIN_SUMMARIZE_SHARD = 8192

# Peak dense bf16 FLOP/s by device_kind (public spec sheets); MFU is achieved
# model FLOP/s over this. Unknown kinds record mfu=null rather than guess.
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def _peak_flops(runtime):
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = getattr(runtime.devices[0], "device_kind", "")
    tf = PEAK_BF16_TFLOPS.get(kind)
    return tf * 1e12 if tf else None


def encoder_flops_per_row(cfg, seq_len: int) -> float:
    """Analytic forward FLOPs for one row at padded length ``seq_len``
    (matmul terms only — 2·M·N·K per matmul; elementwise is noise):
    QKVO projections + score/value matmuls + FFN, summed over layers."""
    d, f, L = cfg.d_model, cfg.d_ff, seq_len
    attn_proj = 8 * L * d * d          # 4 projections × 2·L·d·d
    attn_sdpa = 4 * L * L * d          # QKᵀ and P·V × 2·L²·d
    ffn = 4 * L * d * f                # 2 matmuls × 2·L·d·f
    return cfg.n_layers * (attn_proj + attn_sdpa + ffn) + 2 * d * cfg.n_classes


def _median_windows(run_window, windows: int):
    """run_window() -> (rows_per_sec, p50_ms); returns the median-rate window
    plus the min→max spread as a percentage of the median."""
    samples = [run_window() for _ in range(windows)]
    rates = sorted(s[0] for s in samples)
    med = statistics.median(rates)
    spread = (rates[-1] - rates[0]) / med * 100.0 if med else 0.0
    # p50 latency reported from the median-rate window.
    p50 = min(samples, key=lambda s: abs(s[0] - med))[1]
    return med, p50, spread


def _bench_classify_leg(runtime, *, batch: int, text_len: int, iters: int,
                        windows: int = WINDOWS, model_config=None):
    """One classify throughput leg → dict. Texts are ~text_len bytes so the
    byte tokenizer lands them in the bucket the leg targets."""
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext

    classify = get_op("map_classify_tpu")
    ctx = OpContext(runtime=runtime)
    texts = [
        ("sample record %06d " % i) * max(1, text_len // 20)
        for i in range(batch)
    ]
    payload = {"texts": texts, "topk": 5, "allow_fallback": False}
    if model_config:
        payload["model_config"] = dict(model_config)

    out = classify(payload, ctx)  # warmup: tokenize + compile + run
    assert out["ok"] is True and out.get("fallback") is None, out

    def window():
        lat = []
        t0 = time.perf_counter()
        for _ in range(iters):
            it0 = time.perf_counter()
            o = classify(payload, ctx)
            lat.append(time.perf_counter() - it0)
        wall = time.perf_counter() - t0
        assert o["ok"] is True, o
        lat.sort()
        return batch * iters / wall, lat[len(lat) // 2] * 1000.0

    rows_per_sec, p50_ms, spread = _median_windows(window, windows)
    return {
        "rows_per_sec": round(rows_per_sec, 1),
        "p50_batch_ms": round(p50_ms, 2),
        "spread_pct": round(spread, 2),
        "windows": windows,
        "batch": batch,
    }


def _bench_bert_base(runtime):
    """BERT-base-scale classify (BASELINE.json configs[2]) with an MFU figure."""
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.models.tokenizer import DEFAULT_BUCKETS, bucket_length

    smoke = runtime.platform != "tpu"
    batch = 64 if smoke else BERT_BATCH
    iters = 1 if smoke else BERT_ITERS
    windows = 1 if smoke else WINDOWS
    text_len = 480
    # quant pinned: a fleet-wide TPU_QUANT=int8 env must not silently turn
    # the bf16 reference leg (and the int8 leg's agreement baseline) int8.
    leg = _bench_classify_leg(
        runtime, batch=batch, text_len=text_len, iters=iters,
        windows=windows, model_config={**BERT_CONFIG, "quant": "none"},
    )
    cfg = EncoderConfig(**BERT_CONFIG)
    seq = bucket_length(text_len, [b for b in DEFAULT_BUCKETS
                                   if b <= cfg.max_len])
    flops_row = encoder_flops_per_row(cfg, seq)
    # rows_per_sec is whole-mesh throughput; peak is one chip's — normalize.
    achieved = leg["rows_per_sec"] * flops_row / runtime.n_devices
    peak = _peak_flops(runtime)
    n_params = (
        cfg.vocab_size * cfg.d_model
        + cfg.n_layers * (4 * cfg.d_model**2 + 2 * cfg.d_model * cfg.d_ff)
        + cfg.d_model * cfg.n_classes
    )
    leg.update(
        seq_len=seq,
        params_m=round(n_params / 1e6, 1),
        gflops_per_row=round(flops_row / 1e9, 2),
        achieved_tflops=round(achieved / 1e12, 2),
        mfu=round(achieved / peak, 4) if peak else None,
    )
    return leg


MOE_EXPERTS = 8


def _bench_moe(runtime):
    """Switch-MoE encoder served through ``map_classify_tpu`` — the EP
    capability (SURVEY §2.8, `models/moe.py`) as a recorded throughput
    number beside the dense legs: BERT-base width with every FFN replaced
    by an 8-expert top-1 MoE (8× the FFN parameters, ~dense activated
    FLOPs per token + routing). Single chip ⇒ experts unsharded; the ep>1
    placement itself is proven in tests/dryrun, this leg prices the
    routed-execution overhead."""
    smoke = runtime.platform != "tpu"
    cfg = {
        **BERT_CONFIG, "moe_experts": MOE_EXPERTS,
        "quant": "none",
    } if not smoke else {
        "d_model": 64, "n_heads": 4, "n_layers": 2, "d_ff": 128,
        "max_len": 64, "moe_experts": 4, "quant": "none",
    }
    try:
        leg = _bench_classify_leg(
            runtime,
            batch=64 if smoke else 1024,
            text_len=480,
            iters=1 if smoke else BERT_ITERS,
            windows=1 if smoke else WINDOWS,
            model_config=cfg,
        )
    finally:
        # The 8-expert tree is ~2 GB resident; later legs (train at batch
        # 128, summarize) need that HBM back — measured RESOURCE_EXHAUSTED
        # without this, and a FAILED leg must release it too. Earlier legs'
        # models re-transfer on their next use.
        runtime.clear_params()
    leg["moe_experts"] = cfg["moe_experts"]
    return leg


def _bench_bert_base_int8(runtime, bf16_leg):
    """BERT-base classify with ``model_config {"quant": "int8"}`` (W8A8,
    models/quant.py) — the reference's INT8 device story as an execution
    mode. Records the speedup over the bf16 leg at the same batch and the
    top-1 agreement rate vs bf16 on a diverse batch (the quantization
    fidelity number next to the throughput number)."""
    import numpy as np

    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext

    smoke = runtime.platform != "tpu"
    batch = 64 if smoke else BERT_BATCH
    iters = 1 if smoke else BERT_ITERS
    windows = 1 if smoke else WINDOWS
    leg = _bench_classify_leg(
        runtime, batch=batch, text_len=480, iters=iters, windows=windows,
        model_config={**BERT_CONFIG, "quant": "int8"},
    )
    if bf16_leg and bf16_leg.get("rows_per_sec"):
        leg["speedup_vs_bf16"] = round(
            leg["rows_per_sec"] / bf16_leg["rows_per_sec"], 3
        )

    # Top-1 agreement on a diverse batch: per-row distinct content so the
    # argmax isn't one degenerate class. Same texts through both modes.
    classify = get_op("map_classify_tpu")
    ctx = OpContext(runtime=runtime)
    rng = np.random.default_rng(7)
    words = ["alpha", "risk", "ledger", "breach", "routine", "audit",
             "wire", "flag", "normal", "urgent", "invoice", "metric"]
    texts = [
        " ".join(rng.choice(words, size=60).tolist()) + f" case {i}"
        for i in range(AGREEMENT_ROWS if not smoke else 64)
    ]
    payload = {"texts": texts, "topk": 1, "allow_fallback": False,
               "result_format": "columnar",
               "model_config": {**BERT_CONFIG, "quant": "none"}}
    ref = classify(payload, ctx)
    q = classify({**payload,
                  "model_config": {**BERT_CONFIG, "quant": "int8"}}, ctx)
    assert ref["ok"] is True and q["ok"] is True, (ref, q)
    top1_ref = np.asarray(ref["indices"])[:, 0]
    top1_q = np.asarray(q["indices"])[:, 0]
    leg["agreement_top1"] = round(float((top1_ref == top1_q).mean()), 4)
    leg["agreement_rows"] = len(texts)
    return leg


def _bench_long_ctx(runtime):
    """4k-token classify that provably takes the Pallas flash path, plus a
    model-level dense-vs-flash timing ratio at the same sequence length."""
    import importlib

    # The kernels package re-exports the flash_attention FUNCTION, shadowing
    # the submodule attribute — resolve the module itself for the counters.
    fa = importlib.import_module("agent_tpu.kernels.flash_attention")

    if runtime.platform != "tpu":
        return {"skipped": "flash kernel only selected on real TPU"}

    before = dict(fa.SELECTION_COUNTS)
    leg = _bench_classify_leg(
        runtime, batch=LONG_CTX_BATCH, text_len=4000, iters=LONG_CTX_ITERS,
        model_config=LONG_CTX_CONFIG, windows=NOISY_WINDOWS,
    )
    flash_new = fa.SELECTION_COUNTS["flash"] - before["flash"]
    dense_new = fa.SELECTION_COUNTS["dense"] - before["dense"]
    # The compiled executable must contain the kernel on every layer's
    # attention — a silent dense fallback here is a bench failure, not noise.
    assert flash_new > 0 and dense_new == 0, (
        f"long-ctx leg did not take the flash path "
        f"(flash+{flash_new}, dense+{dense_new})"
    )
    leg["flash_selected"] = True
    leg["seq_len"] = 4096
    try:
        leg["flash_vs_dense_speedup"] = round(_flash_vs_dense(runtime), 2)
    except Exception as exc:  # noqa: BLE001 — ratio is informative, not vital
        leg["flash_vs_dense_error"] = f"{type(exc).__name__}: {exc}"[:200]
    try:
        # The 8k point, where the dense path's [L, L] score materialization
        # thrashes HBM — recorded so the kernel docstring's 8k headline is a
        # driver artifact, not prose (batch 2 keeps dense's scores in HBM).
        leg["flash_vs_dense_8k"] = round(
            _flash_vs_dense(runtime, batch=2, seq=8192), 2
        )
    except Exception as exc:  # noqa: BLE001
        leg["flash_vs_dense_8k_error"] = f"{type(exc).__name__}: {exc}"[:200]
    return leg


def _flash_vs_dense(runtime, batch: int = 4, seq: int = 4096):
    """Per-call attention time, dense XLA vs the Pallas kernel, at the
    long-ctx leg's shape. Small batch: the dense path materializes
    [B, H, L, L] scores in HBM (the kernel's whole advantage), which caps B
    at 4k ctx.

    Methodology: the host→device round trip costs ~100 ms on a tunneled
    chip, so single-call wall times are RTT, not kernel time. Each path is
    timed as a ``fori_loop`` chaining N calls inside ONE program, synced by
    a scalar fetch; per-call = (t_21 − t_1) / 20."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from agent_tpu.kernels.flash_attention import flash_attention
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.models.layers import dot_product_attention

    cfg = EncoderConfig(**LONG_CTX_CONFIG)
    d_head = cfg.d_model // cfg.n_heads
    rng = np.random.default_rng(0)
    shape = (batch, cfg.n_heads, seq, d_head)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape), dtype=cfg.compute_dtype)
        for _ in range(3)
    )
    m = jnp.ones((batch, 1, 1, seq), dtype=jnp.int32)
    fetch = jax.jit(lambda o: jnp.sum(o[:1, :1, :8, :8]))

    def timed(attn, n, reps: int = 5):
        f = jax.jit(
            lambda q, k, v, m: jax.lax.fori_loop(
                0, n, lambda i, x: attn(x, k, v, m), q
            )
        )
        float(fetch(f(q, k, v, m)))  # compile
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(fetch(f(q, k, v, m)))
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    def per_call(attn):
        return (timed(attn, 21) - timed(attn, 1)) / 20

    flash = functools.partial(flash_attention, min_key_len=0)
    return per_call(dot_product_attention) / per_call(flash)


def _bench_train(runtime):
    """Training throughput at BERT-base scale: one jitted fwd+bwd+adamw step
    (models/train.py), examples/sec and training MFU (flops ≈ 3× forward).

    Steps chain on device (step i+1 consumes step i's params), so timing N
    dispatches and syncing once amortizes the host round trip the same way
    the flash ratio measurement does."""
    import jax
    import numpy as np

    from agent_tpu.models import encoder
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.models.train import make_train_step

    smoke = runtime.platform != "tpu"
    cfg = EncoderConfig(
        **(BERT_CONFIG if not smoke
           else {"d_model": 64, "n_heads": 4, "n_layers": 2, "d_ff": 128,
                 "max_len": 64})
    )
    batch = 32 if smoke else TRAIN_BATCH
    seq = 64 if smoke else 512
    steps = 2 if smoke else TRAIN_STEPS

    # Remat-free training at batch 128 budgets essentially the whole chip;
    # serving models resident from earlier legs would shave the headroom.
    runtime.clear_params()
    params = jax.device_put(
        encoder.init_params(cfg, model_id="bench-train"), runtime.replicated()
    )
    # remat=False when the TRAINING flash gate selects the kernel: its
    # backward stores no [B, H, L, L] scores, so at batch 128 the whole
    # backward fits without rematerialization — the measured optimum (see
    # TRAIN_BATCH note). selects_flash_train (not the attn_fn identity!)
    # also covers the mesh wrapper's dp/tp-divisibility dense fallback.
    # Off-TPU (dense path) the smoke shapes are tiny and need no remat; a
    # TPU run with pallas disabled keeps remat=True to avoid the ~39 GB
    # dense score store.
    import importlib

    fa = importlib.import_module("agent_tpu.kernels.flash_attention")
    from agent_tpu.models.layers import dot_product_attention

    attn_fn = runtime.train_attention_fn()
    flash_train = (
        attn_fn is not dot_product_attention
        and fa.selects_flash_train(
            seq, batch=batch, n_heads=cfg.n_heads, mesh=runtime.mesh
        )
    )
    init_state, step = make_train_step(
        cfg, remat=not (smoke or flash_train), attn_fn=attn_fn
    )
    opt_state = init_state(params)
    rng = np.random.default_rng(0)
    ids = runtime.put_batch(
        rng.integers(4, cfg.vocab_size, (batch, seq)).astype(np.int32)
    )
    mask = runtime.put_batch(np.ones((batch, seq), dtype=np.int32))
    labels = runtime.put_batch(
        rng.integers(0, cfg.n_classes, (batch,)).astype(np.int32)
    )

    # TWO warmup steps: the first compiles for the init-state avals, the
    # second for the steady-state ones (the returned opt_state's weak-typed
    # scalars become strong, which retriggers compilation exactly once).
    before_ft = fa.SELECTION_COUNTS.get("flash_train", 0)
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, ids, mask, labels)
        float(loss)
    if flash_train:
        # The remat=False decision above is only safe on the kernel path —
        # prove the compiled step actually contains it.
        assert fa.SELECTION_COUNTS.get("flash_train", 0) > before_ft, (
            "train leg disabled remat but the flash kernel was not selected"
        )

    def window():
        nonlocal params, opt_state
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, ids, mask, labels)
        final = float(loss)  # one sync for the chained steps
        wall = time.perf_counter() - t0
        assert final == final, "train loss is NaN"
        return batch * steps / wall, wall * 1000.0 / steps

    ex_per_sec, step_ms, spread = _median_windows(window, WINDOWS)
    flops_ex = 3 * encoder_flops_per_row(cfg, seq)  # fwd + ~2× for bwd
    achieved = ex_per_sec * flops_ex / runtime.n_devices
    peak = _peak_flops(runtime)
    return {
        "examples_per_sec": round(ex_per_sec, 1),
        "step_ms": round(step_ms, 2),
        "spread_pct": round(spread, 2),
        "batch": batch,
        "seq_len": seq,
        "gflops_per_example": round(flops_ex / 1e9, 2),
        "achieved_tflops": round(achieved / 1e12, 2),
        "mfu": round(achieved / peak, 4) if peak else None,
    }


# Batch 128 × seq 2048 = 262k tokens per step; batch 16 measured 8 points
# of MFU lower (too little work per dispatch), 256 adds nothing (405 vs
# 400 ex/s) for 2× the activation memory.
TRAIN_LONG_CTX_BATCH = 128
TRAIN_LONG_CTX_SEQ = 2048
TRAIN_LONG_CTX_STEPS = 4


def _bench_train_long_ctx(runtime):
    """Long-context training (seq 2048) through the DIFFERENTIABLE Pallas
    flash kernel — fwd and bwd both streaming, no [L, L] score matrices in
    HBM in either direction. Asserts the ``flash_train`` selection counter
    ticked and ``dense_train`` did not: the compiled train step provably
    contains the kernel pair, the same proof discipline as the serving
    ``long_ctx`` leg. This leg did not exist before the backward kernel —
    dense-backward training at 2k+ context OOMed or crawled."""
    import importlib

    import jax
    import numpy as np

    from agent_tpu.models import encoder
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.models.train import make_train_step

    fa = importlib.import_module("agent_tpu.kernels.flash_attention")
    if runtime.platform != "tpu":
        return {"skipped": "flash kernel only selected on real TPU"}

    cfg = EncoderConfig(**{**LONG_CTX_CONFIG, "max_len": TRAIN_LONG_CTX_SEQ})
    batch, seq, steps = (
        TRAIN_LONG_CTX_BATCH, TRAIN_LONG_CTX_SEQ, TRAIN_LONG_CTX_STEPS,
    )
    params = jax.device_put(
        encoder.init_params(cfg, model_id="bench-train-longctx"),
        runtime.replicated(),
    )
    before = dict(fa.SELECTION_COUNTS)
    # remat=False ON PURPOSE: the flash backward keeps [L, L] score
    # tensors out of HBM in both directions, so 262k tokens of activations
    # fit without rematerialization — measured 1.36× faster than the
    # remat step (400 vs 295 ex/s). The seq-512 train leg now does the
    # same (FLASH_TRAIN_MIN_KEY_LEN gates at 512). Disabling remat is only
    # safe on the kernel path, so consult the selection predicate (which
    # includes the mesh wrapper's dp/tp fallback) rather than assuming —
    # a dense fallback here would store 262k-token score tensors and OOM
    # before the post-warmup counter assert could explain why.
    if not fa.selects_flash_train(
        seq, batch=batch, n_heads=cfg.n_heads, mesh=runtime.mesh
    ):
        return {"skipped": "flash-train kernel not selectable on this mesh"}
    init_state, step = make_train_step(
        cfg, remat=False, attn_fn=runtime.train_attention_fn()
    )
    opt_state = init_state(params)
    rng = np.random.default_rng(0)
    ids = runtime.put_batch(
        rng.integers(4, cfg.vocab_size, (batch, seq)).astype(np.int32)
    )
    mask = runtime.put_batch(np.ones((batch, seq), dtype=np.int32))
    labels = runtime.put_batch(
        rng.integers(0, cfg.n_classes, (batch,)).astype(np.int32)
    )
    for _ in range(2):  # two warmups, same rationale as _bench_train
        params, opt_state, loss = step(params, opt_state, ids, mask, labels)
        float(loss)
    flash_new = fa.SELECTION_COUNTS.get("flash_train", 0) - before.get(
        "flash_train", 0
    )
    dense_new = fa.SELECTION_COUNTS.get("dense_train", 0) - before.get(
        "dense_train", 0
    )
    assert flash_new > 0 and dense_new == 0, (
        f"long-ctx train leg did not take the flash path "
        f"(flash_train+{flash_new}, dense_train+{dense_new})"
    )

    def window():
        nonlocal params, opt_state
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, ids, mask,
                                           labels)
        final = float(loss)
        wall = time.perf_counter() - t0
        assert final == final, "long-ctx train loss is NaN"
        return batch * steps / wall, wall * 1000.0 / steps

    ex_per_sec, step_ms, spread = _median_windows(window, WINDOWS)
    flops_ex = 3 * encoder_flops_per_row(cfg, seq)
    achieved = ex_per_sec * flops_ex / runtime.n_devices
    peak = _peak_flops(runtime)
    return {
        "examples_per_sec": round(ex_per_sec, 1),
        "step_ms": round(step_ms, 2),
        "spread_pct": round(spread, 2),
        "batch": batch,
        "seq_len": seq,
        "flash_train_selected": True,
        "gflops_per_example": round(flops_ex / 1e9, 2),
        "achieved_tflops": round(achieved / 1e12, 2),
        "mfu": round(achieved / peak, 4) if peak else None,
    }


SUMMARIZE_ITERS = 4


def _bench_summarize(runtime, batch: int = SUMMARIZE_BATCH,
                     max_new: int = SUMMARIZE_MAX_NEW,
                     iters: int = SUMMARIZE_ITERS, num_beams: int = 1,
                     quant: str = None):
    """Decode throughput through the op. ``num_beams=4`` is the reference's
    unconditional decode mode (``/root/reference/ops/map_summarize.py:57``;
    greedy is this framework's documented default-divergence) — the beam leg
    records what that output-quality parity costs. tok/s counts EMITTED
    tokens; beam explores num_beams× more decoder compute per emitted token.
    ``quant`` serves the mode via ``model_config`` ("w8a16" is the
    decode-targeted weight-only mode, models/quant.py)."""
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext

    summarize = get_op("map_summarize")
    ctx = OpContext(runtime=runtime)
    payload = {
        "texts": ["a document to compress " * 20] * batch,
        "max_length": max_new,
        **({"num_beams": num_beams} if num_beams > 1 else {}),
        **({"model_config": {"quant": quant}} if quant else {}),
    }
    summarize(payload, ctx)  # warmup/compile

    # Several op calls per window: one ~180 ms decode alone is dominated by
    # the host-device round trip's variance (see tpu tunnel notes).
    def window():
        t0 = time.perf_counter()
        for _ in range(iters):
            out = summarize(payload, ctx)
            assert out["ok"] is True, out  # a failed call must not be timed
        dt = time.perf_counter() - t0
        return batch * max_new * iters / dt, dt * 1000.0

    tok_per_sec, _, spread = _median_windows(window, WINDOWS)
    # Per-chip normalization like the classify flat field (ISSUE 15
    # satellite): real TPU legs engage the whole mesh; on host backends the
    # forced virtual devices share one CPU and are not chips.
    chips = runtime.n_devices if runtime.platform == "tpu" else 1
    leg = {"decode_tok_per_sec": round(tok_per_sec, 1),
           "tok_per_sec_per_chip": round(tok_per_sec / chips, 1),
           "n_chips_used": chips,
           "spread_pct": round(spread, 2), "windows": WINDOWS,
           "iters": iters, "num_beams": num_beams}
    if quant:
        leg["quant"] = quant
    return leg


def _w8a16_decode_agreement(runtime, num_beams: int = 4, max_new: int = 16):
    """Token/sequence agreement of W8A16 decode vs the bf16 reference over
    ≥``AGREEMENT_ROWS`` rows (smoke: 64) at the serving seq2seq config —
    the quantization-fidelity number next to the w8a16 throughput legs.

    Model-level on purpose: comparing emitted TOKEN arrays (not detokenized
    strings) makes the metric exact, and the op above already proves the
    serving contract. Chunked decode bounds the [B·K, H, T, D] cache HBM.

    ``agreement_control_token`` is the NO-QUANT control: the same bf16
    reference against the f32 decode of the SAME weights. Free-running
    decode on the bench's untrained deterministic-random model amplifies
    any perturbation (near-uniform next-token distributions + cascades), so
    the control prices that substrate noise — measured on CPU dev runs the
    bf16-vs-f32 control (0.976) disagrees MORE than w8a16-vs-bf16 (0.988):
    weight-only int8 adds no token flips beyond existing compute-dtype
    noise, which is the claim that matters. Judge agreement_token against
    the control, not against 1.0."""
    import jax
    import numpy as np
    from dataclasses import replace

    from agent_tpu.models import quant, seq2seq

    smoke = runtime.platform != "tpu"
    rows = 64 if smoke else AGREEMENT_ROWS
    chunk = 64 if smoke else 1024
    # Smoke shrinks the model like the other legs do (CPU beam-4 decode at
    # the serving config takes minutes/row-batch); TPU measures the real one.
    cfg = seq2seq.Seq2SeqConfig() if not smoke else seq2seq.Seq2SeqConfig(
        d_model=64, n_heads=4, n_enc_layers=1, n_dec_layers=1, d_ff=128,
        max_src_len=64, max_tgt_len=16, dtype="float32",
    )
    ctl_cfg = replace(
        cfg, dtype="float32" if cfg.dtype != "float32" else "bfloat16"
    )
    params = seq2seq.init_params(cfg, model_id="bench-w8a16-agree")
    qparams = quant.quantize_for_family("seq2seq", params, "w8a16")
    params = jax.device_put(params, runtime.replicated())
    qparams = jax.device_put(qparams, runtime.replicated())

    def make_gen(c):
        return jax.jit(
            lambda p, i, m: seq2seq.beam_generate(
                p, i, m, c, max_new, num_beams=num_beams,
            )
        )

    gen, gen_ctl = make_gen(cfg), make_gen(ctl_cfg)
    rng = np.random.default_rng(11)
    src_len = 32 if smoke else 64
    tok_match = ctl_match = tok_total = seq_match = 0
    for s in range(0, rows, chunk):
        n = min(chunk, rows - s)
        ids = rng.integers(4, cfg.vocab_size, size=(n, src_len)).astype(
            np.int32
        )
        mask = np.ones((n, src_len), dtype=np.int32)
        ref = np.asarray(gen(params, ids, mask)[0])
        got = np.asarray(gen(qparams, ids, mask)[0])
        ctl = np.asarray(gen_ctl(params, ids, mask)[0])
        tok_match += int((ref == got).sum())
        ctl_match += int((ref == ctl).sum())
        tok_total += ref.size
        seq_match += int((ref == got).all(axis=1).sum())
    return {
        "agreement_token": round(tok_match / tok_total, 4),
        "agreement_seq": round(seq_match / rows, 4),
        "agreement_control_token": round(ctl_match / tok_total, 4),
        "agreement_rows": rows,
        "agreement_num_beams": num_beams,
    }


def _bench_summarize_w8a16(runtime, greedy_ref, beam_ref):
    """W8A16 weight-only decode (models/quant.py wdense/wproj_*): the
    memory-bound recipe for [rows, d]-thin decode matmuls — int8-resident
    weights (half the bf16 HBM bytes) dequantized in-register, activations
    untouched, NO dynamic quantization pass. Records greedy and beam-4
    throughput, the ``w8a16_vs_bf16`` speedups vs the recorded bf16 legs,
    and token/sequence agreement over ≥``AGREEMENT_ROWS`` rows.

    Returns (greedy_leg, beam_leg); agreement fields ride on the beam leg
    (beam-4 is the reference's decode mode and the mode the speedup bar
    ≥1.15 targets)."""
    smoke = runtime.platform != "tpu"
    kw = dict(batch=8, max_new=8, iters=1) if smoke else {}
    leg = _bench_summarize(runtime, quant="w8a16", **kw)
    if not smoke and greedy_ref and greedy_ref.get("decode_tok_per_sec"):
        leg["w8a16_vs_bf16"] = round(
            leg["decode_tok_per_sec"] / greedy_ref["decode_tok_per_sec"], 3
        )
    beam = _bench_summarize(runtime, num_beams=4, quant="w8a16", **kw)
    if not smoke and beam_ref and beam_ref.get("decode_tok_per_sec"):
        beam["w8a16_vs_bf16"] = round(
            beam["decode_tok_per_sec"] / beam_ref["decode_tok_per_sec"], 3
        )
    beam.update(_w8a16_decode_agreement(runtime))
    return leg, beam


def _bench_csv_index(tmpdir: str, n_rows: int = 1_000_000, repeats: int = 3):
    """Index-build MB/s, best of ``repeats`` cold builds of a ~38 MB file.

    The memchr scanner builds at ~1 GB/s, so the file must be big enough to
    out-time the per-build constant costs, and best-of-N (fresh file per
    build ⇒ every build is index-cold, page-cache warm after the first)
    filters host-contention spikes the way the windowed legs do."""
    import shutil

    from agent_tpu.data.csv_index import CsvIndex

    src = os.path.join(tmpdir, "bench_rows_0.csv")
    with open(src, "w") as f:
        f.write("id,text,risk\n")
        for i in range(n_rows):
            f.write(f'{i},"record {i} with some text payload",{i % 97}\n')
    best = 0.0
    for r in range(repeats):
        # Fresh path per repeat: CsvIndex caches by (path, size, mtime), so a
        # copy keeps every build index-cold while the page cache stays warm.
        path = src if r == 0 else os.path.join(tmpdir, f"bench_rows_{r}.csv")
        if r > 0:
            shutil.copy(src, path)
        size_mb = os.path.getsize(path) / 1e6
        t0 = time.perf_counter()
        index = CsvIndex.for_file(path)  # fresh temp file ⇒ cold index build
        dt = time.perf_counter() - t0
        assert index.n_data_rows == n_rows, index.n_data_rows
        if r > 0:
            os.remove(path)
        best = max(best, size_mb / dt)
    os.remove(src)
    return best


def _drain_until_done(agent, controller, depth: int = 2, workers=None,
                      autotune=None, double_buffer=None) -> float:
    """Run the pipelined runner until the controller drains; returns the wall
    seconds to the drain moment (not thread-teardown time). ``workers``/
    ``autotune``/``double_buffer`` override the staging-pool config
    (ISSUE 6); None keeps the STAGE_* defaults."""
    from agent_tpu.agent.pipeline import PipelineRunner

    agent.running = True
    done = {}

    def watch():
        while not controller.drained():
            time.sleep(0.01)
        done["wall"] = time.perf_counter() - t0
        agent.running = False

    watcher = threading.Thread(target=watch, daemon=True)
    t0 = time.perf_counter()
    watcher.start()
    PipelineRunner(agent, depth=depth, workers=workers, autotune=autotune,
                   double_buffer=double_buffer).run()
    watcher.join(timeout=10)
    return done.get("wall", time.perf_counter() - t0)


def _bench_drain(runtime, n_rows: int = DRAIN_ROWS,
                 shard_size: int = DRAIN_SHARD_SIZE):
    """Framework-level drain: controller shards a CSV into tasks, one agent
    drains them over real HTTP through the pipelined runner — the
    BASELINE.json 10M-row drain shape at bench scale.

    Returns (classify_only_leg, mixed_leg): classify-only is the r01/r02
    trend line (directly comparable to the pure-op number — the double-
    buffering win shows up as drain ≈ pure-op); mixed adds summarize shards,
    the literal "classify+summarize job" of the north star."""
    import tempfile

    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    def check_all_ok(controller):
        counts = controller.counts()
        assert counts.get("failed", 0) == 0, counts
        # Soft-failed shards are recorded SUCCEEDED — check result bodies
        # so a drain that classified nothing can't report throughput.
        bad = [
            r for r in controller.results().values()
            if not (isinstance(r, dict) and r.get("ok") is True)
        ]
        assert not bad, f"{len(bad)} shards returned non-ok results"

    classify_extra = {"text_field": "text", "allow_fallback": False,
                      "result_format": "columnar"}
    # bf16, NOT int8, on purpose: decode steps are [B, 256]-shaped matmuls,
    # small enough that W8A8's dynamic activation quantization costs more
    # than the MXU saves — measured 3,983 rows/s int8 vs 4,980 bf16 at
    # B=1024 through this op. int8's win is the big-matmul encoders
    # (BERT-base leg: 1.21×); the summarize levers are decode BATCH (4,980 →
    # 8,093 rows/s from B=1024 → 8192 — see DRAIN_SUMMARIZE_SHARD) and
    # W8A16 weight-only quant (no activation-quant pass, half the weight
    # HBM bytes — the summarize_w8a16 legs record it; the drain default
    # stays bf16 until a recorded w8a16 drain win justifies flipping it).
    summarize_extra = {"text_field": "text", "max_length": SUMMARIZE_MAX_NEW,
                       "allow_fallback": False}

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "drain.csv")
        with open(path, "w") as f:
            f.write("id,text,risk\n")
            for i in range(n_rows):
                f.write(f'{i},"drain record {i} with a payload of text",{i % 89}\n')

        from agent_tpu.config import SloConfig

        # SLO-judged drain (ISSUE 8): op-keyed objectives with a generous
        # p99 (bulk shards legitimately run seconds) so the health leg
        # records attainment/verdict without paging a healthy bench.
        controller = Controller(lease_ttl_sec=600.0, slo=SloConfig(spec=(
            '[{"name": "classify", "op": "map_classify_tpu",'
            ' "p99_ms": 600000, "availability": 0.999},'
            ' {"name": "summarize", "op": "map_summarize",'
            ' "p99_ms": 600000, "availability": 0.999}]'
        )))
        with ControllerServer(controller) as server:
            cfg = Config(
                agent=AgentConfig(
                    controller_url=server.url,
                    agent_name="bench-drain",
                    tasks=("map_classify_tpu", "map_summarize"),
                    idle_sleep_sec=0.0,
                )
            )
            agent = Agent(config=cfg, session=requests.Session(),
                          runtime=runtime)
            agent._profile = {"tier": "bench"}

            # Warm the executable cache outside the timed window (compile is
            # a once-per-process cost, reference handle-singleton semantics).
            controller.submit_csv_job(
                path, total_rows=shard_size, shard_size=shard_size,
                map_op="map_classify_tpu", extra_payload=classify_extra,
            )
            controller.submit_csv_job(
                path, total_rows=DRAIN_SUMMARIZE_SHARD,
                shard_size=DRAIN_SUMMARIZE_SHARD,
                map_op="map_summarize", extra_payload=summarize_extra,
            )
            _drain_until_done(agent, controller)
            check_all_ok(controller)

            # Leg 1: classify-only (trend line vs pure-op throughput).
            controller.submit_csv_job(
                path, total_rows=n_rows, shard_size=shard_size,
                map_op="map_classify_tpu", extra_payload=classify_extra,
            )
            wall = _drain_until_done(agent, controller)
            check_all_ok(controller)
            classify_leg = {
                "rows_per_sec": round(n_rows / wall, 1),
                "rows": n_rows,
                "pipelined": True,
            }

            # Leg 2: mixed classify+summarize, one drain. Snapshot the result
            # keys first: Controller.results() is cumulative across legs, and
            # the busy accounting below must cover ONLY this leg's shards.
            # Same for the scraped metrics: counters are cumulative, so the
            # per-leg attribution is the scrape DELTA across the leg.
            from agent_tpu.obs.scrape import (
                fetch_metrics_text,
                op_phase_seconds,
            )

            drain_ops = ("map_classify_tpu", "map_summarize")
            pre = fetch_metrics_text(server.url)
            span_pre = (
                op_phase_seconds(pre, drain_ops) if pre is not None else None
            )
            seen_jobs = set(controller.results())
            controller.submit_csv_job(
                path, total_rows=n_rows, shard_size=shard_size,
                map_op="map_classify_tpu", extra_payload=classify_extra,
            )
            controller.submit_csv_job(
                path, total_rows=DRAIN_SUMMARIZE_ROWS,
                shard_size=DRAIN_SUMMARIZE_SHARD,
                map_op="map_summarize", extra_payload=summarize_extra,
            )
            wall = _drain_until_done(agent, controller)
            check_all_ok(controller)
            # Per-op spans (dispatch + deferred fetch): primary source is
            # the scraped /v1/metrics fleet series (execute+fetch phase
            # sums, delta across the leg); utils.spans result-body summing
            # is the fallback when scraping is unavailable.
            post = fetch_metrics_text(server.url)
            span_s: dict = {}
            span_source = "scrape"
            if span_pre is not None and post is not None:
                span_post = op_phase_seconds(post, drain_ops)
                span_s = {
                    op: span_post[op] - span_pre[op] for op in drain_ops
                }
            if not any(span_s.values()):
                from agent_tpu.utils.spans import op_span_ms

                span_source = "result_bodies"
                span_ms = op_span_ms(
                    (
                        r for job_id, r in controller.results().items()
                        if job_id not in seen_jobs
                    ),
                    drain_ops,
                )
                span_s = {op: span_ms[op] / 1e3 for op in drain_ops}
            # Slowest-job trace breakdown (ISSUE 5 satellite): fetched from
            # GET /v1/trace/{job_id} so a regression in the trace path
            # fails the bench loudly instead of rotting silently.
            from agent_tpu.obs import trace as obs_trace
            from agent_tpu.obs.scrape import slowest_trace
            from agent_tpu.obs.trace import phase_breakdown

            trace_line = None
            if obs_trace.enabled():
                worst = slowest_trace(server.url)
                assert worst is not None, (
                    "trace path broken: /v1/traces or /v1/trace/{job_id} "
                    "returned nothing for a drained leg"
                )
                trace_line = phase_breakdown(worst)
                print(f"[slowest shard] {trace_line}", flush=True)
            # Fleet health rollup (ISSUE 8 satellite): the verdict and the
            # per-op attainment/MFU ride the artifact as flat fields; an
            # unreachable /v1/health FAILS the leg instead of silently
            # omitting them.
            from agent_tpu.obs.scrape import fetch_health

            health = fetch_health(server.url)
            assert health is not None, (
                "health path broken: GET /v1/health unreachable for a "
                "drained leg"
            )
            print(f"[health] verdict={health['verdict']}", flush=True)
            slo_attain = {
                o.get("op", o["objective"]): o.get("attainment")
                for o in health["slo"]["objectives"]
            }
            mfu_by_op: dict = {}
            for row in (health.get("agents") or {}).values():
                for op, v in (row.get("mfu") or {}).items():
                    mfu_by_op[op] = v
            # Usage showback rollup (ISSUE 9): the mixed leg's billed
            # device/host seconds and rows off GET /v1/usage — an
            # unreachable report fails the leg like an unreachable health.
            from agent_tpu.obs.scrape import fetch_json as _fetch_json

            usage = _fetch_json(server.url, "/v1/usage")
            assert isinstance(usage, dict) and usage.get("enabled"), (
                "usage path broken: GET /v1/usage unreachable for a "
                "drained leg"
            )
            total_rows = n_rows + DRAIN_SUMMARIZE_ROWS
            mixed_leg = {
                "health_verdict": health["verdict"],
                "slo_attainment": slo_attain,
                "mfu": mfu_by_op or None,
                "usage_device_seconds": usage["totals"]["device_seconds"],
                "usage_host_seconds": usage["totals"]["host_seconds"],
                "usage_rows": usage["totals"]["rows"],
                "usage_billed_tasks": usage["billed_tasks"],
                "rows_per_sec": round(total_rows / wall, 1),
                "classify_rows": n_rows,
                "summarize_rows": DRAIN_SUMMARIZE_ROWS,
                "classify_span_s": round(span_s["map_classify_tpu"], 2),
                "summarize_span_s": round(span_s["map_summarize"], 2),
                "span_source": span_source,
                "slowest_trace": trace_line,
                "wall_s": round(wall, 2),
                "pipelined": True,
            }
    return classify_leg, mixed_leg


def _drain_harness(runtime, n_rows, extra, td, wire_binary=True):
    """(controller, server, agent, csv_path) for one drain leg — shared by
    the staged-parallel and binary-wire legs (ISSUE 6)."""
    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    path = os.path.join(td, "drain.csv")
    if not os.path.exists(path):
        with open(path, "w") as f:
            f.write("id,text,risk\n")
            for i in range(n_rows):
                f.write(
                    f'{i},"drain record {i} with a payload of text",{i % 89}\n'
                )
    controller = Controller(lease_ttl_sec=600.0, wire_binary=wire_binary)
    server = ControllerServer(controller).start()
    cfg = Config(agent=AgentConfig(
        controller_url=server.url, agent_name="bench-drain-dp",
        tasks=("map_classify_tpu",), idle_sleep_sec=0.0,
    ))
    agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
    agent._profile = {"tier": "bench"}
    return controller, server, agent, path


def _scrape_http_bytes(url):
    """{(route, direction): bytes} from controller_http_bytes_total."""
    from agent_tpu.obs.metrics import parse_exposition
    from agent_tpu.obs.scrape import fetch_metrics_text

    text = fetch_metrics_text(url)
    out = {}
    if text is None:
        return out
    try:
        samples = parse_exposition(text)
    except ValueError:
        return out
    for labels, value in samples.get("controller_http_bytes_total", []):
        out[(labels.get("route"), labels.get("direction"))] = value
    return out


def _bench_drain_staged(runtime, n_rows: int = DRAIN_ROWS,
                        shard_size: int = DRAIN_SHARD_SIZE):
    """``drain_staged_parallel`` leg (ISSUE 6): the classify drain with the
    staging pool at 4 autotuned workers + double-buffered feed vs the
    single-worker reference — same rows, bit-identical results asserted."""
    import tempfile

    extra = {"text_field": "text", "allow_fallback": False,
             "result_format": "columnar"}
    leg = {"rows": n_rows}
    with tempfile.TemporaryDirectory() as td:
        results = {}
        for key, workers, autotune in (("workers_1", 1, False),
                                       ("workers_4", 4, True)):
            controller, server, agent, path = _drain_harness(
                runtime, n_rows, extra, td
            )
            try:
                # Warm outside the timed window (compile is per-process).
                controller.submit_csv_job(
                    path, total_rows=shard_size, shard_size=shard_size,
                    map_op="map_classify_tpu", extra_payload=extra,
                )
                _drain_until_done(agent, controller, workers=workers,
                                  autotune=autotune)
                warm_jobs = set(controller.results())
                controller.submit_csv_job(
                    path, total_rows=n_rows, shard_size=shard_size,
                    map_op="map_classify_tpu", extra_payload=extra,
                )
                wall = _drain_until_done(agent, controller, workers=workers,
                                         autotune=autotune)
                counts = controller.counts()
                assert counts.get("failed", 0) == 0, counts
                leg[f"{key}_rows_per_sec"] = round(n_rows / wall, 1)
                results[key] = {
                    controller.job(j).payload["start_row"]:
                        (r["indices"], r["scores"])
                    for j, r in controller.results().items()
                    if j not in warm_jobs
                }
            finally:
                server.stop()
        assert results["workers_1"] == results["workers_4"], (
            "multi-worker staging diverged from the single-worker reference"
        )
        leg["bit_identical"] = True
        leg["speedup"] = round(
            leg["workers_4_rows_per_sec"] / leg["workers_1_rows_per_sec"], 3
        )
        leg["rows_per_sec"] = leg["workers_4_rows_per_sec"]
    return leg


def _bench_drain_binary(runtime, n_rows: int = DRAIN_ROWS,
                        shard_size: int = DRAIN_SHARD_SIZE):
    """``drain_binary_wire`` leg (ISSUE 6): the classify drain over real
    HTTP with the binary shard wire negotiated vs a JSON-only controller —
    rows/sec plus REAL wire bytes/row (server-side Content-Length deltas on
    /v1/leases out + /v1/results in) and the shrink factor."""
    import tempfile

    extra = {"text_field": "text", "allow_fallback": False,
             "result_format": "columnar"}
    leg = {"rows": n_rows}
    with tempfile.TemporaryDirectory() as td:
        for key, wire_binary in (("json", False), ("b1", True)):
            controller, server, agent, path = _drain_harness(
                runtime, n_rows, extra, td, wire_binary=wire_binary
            )
            try:
                controller.submit_csv_job(
                    path, total_rows=shard_size, shard_size=shard_size,
                    map_op="map_classify_tpu", extra_payload=extra,
                )
                _drain_until_done(agent, controller)
                pre = _scrape_http_bytes(server.url)
                controller.submit_csv_job(
                    path, total_rows=n_rows, shard_size=shard_size,
                    map_op="map_classify_tpu", extra_payload=extra,
                )
                wall = _drain_until_done(agent, controller)
                counts = controller.counts()
                assert counts.get("failed", 0) == 0, counts
                post = _scrape_http_bytes(server.url)
                data_bytes = sum(
                    post.get(k, 0.0) - pre.get(k, 0.0)
                    for k in (("/v1/results", "in"), ("/v1/leases", "out"))
                )
                leg[f"{key}_rows_per_sec"] = round(n_rows / wall, 1)
                leg[f"{key}_bytes_per_row"] = round(data_bytes / n_rows, 1)
            finally:
                server.stop()
        if leg.get("b1_bytes_per_row"):
            leg["wire_shrink_x"] = round(
                leg["json_bytes_per_row"] / leg["b1_bytes_per_row"], 2
            )
        leg["rows_per_sec"] = leg["b1_rows_per_sec"]
        leg["bytes_per_row"] = leg["b1_bytes_per_row"]
    return leg


def _fleet_drain_mode(
    csv_path, extra, warm_file, *, n_agents, devices_per_agent,
    mesh_shape, name_prefix, log_dir, rows, shard_size,
):
    """One fleet/mesh drain over real HTTP → (rows_per_sec, per-agent shard
    counts, results keyed by start_row). Children are spawned, warmed, and
    READY (first controller poll seen) before the timed submit, so
    per-process compile cost stays outside the window — the same warm-
    exclusion methodology as every other drain leg."""
    from agent_tpu.agent import fleet
    from agent_tpu.config import SchedConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    # Fair policy on purpose: idle-preference + queue_depth-aware grants
    # are what spread shards across the fleet (ISSUE 7 tentpole a).
    controller = Controller(
        lease_ttl_sec=600.0, sched=SchedConfig(policy="fair")
    )
    server = ControllerServer(controller).start()
    handle = fleet.spawn_fleet(
        n_agents, devices_per_agent,
        controller_url=server.url, tasks="map_classify_tpu",
        platform="cpu", name_prefix=name_prefix, mesh_shape=mesh_shape,
        warm_file=warm_file, log_dir=log_dir,
        extra_env={
            "IDLE_SLEEP_SEC": "0.02",
            # One virtual chip = one core's worth of BLAS: a 1-agent
            # reference that borrows the whole host's thread pool would
            # deflate every scaling ratio derived from it.
            "OMP_NUM_THREADS": "1",
            "OPENBLAS_NUM_THREADS": "1",
        },
    )
    try:
        assert fleet.wait_for_agents(
            controller.agents_summary, handle.names, timeout=300.0,
            fleet=handle,
        ), (
            f"fleet {name_prefix} not ready "
            f"(failures={handle.poll_failures()})"
        )
        t0 = time.perf_counter()
        shard_ids, _ = controller.submit_csv_job(
            csv_path, total_rows=rows, shard_size=shard_size,
            map_op="map_classify_tpu", extra_payload=extra,
        )
        deadline = time.monotonic() + 600.0
        while not controller.drained():
            assert time.monotonic() < deadline, (
                f"fleet {name_prefix} drain stuck: {controller.counts()}"
            )
            assert not handle.poll_failures(), (
                f"fleet member died: {handle.poll_failures()}"
            )
            time.sleep(0.02)
        wall = time.perf_counter() - t0
        counts = controller.counts()
        assert counts.get("failed", 0) == 0, counts
        per_agent = {name: 0 for name in handle.names}
        results = {}
        for jid in shard_ids:
            snap = controller.job_snapshot(jid)
            r = snap["result"]
            assert isinstance(r, dict) and r.get("ok") is True, (jid, r)
            results[controller.job(jid).payload["start_row"]] = (
                r["indices"], r["scores"]
            )
            if snap["agent"] in per_agent:
                per_agent[snap["agent"]] += 1
        return rows / wall, per_agent, results
    finally:
        handle.stop()
        server.stop()


def _bench_drain_multichip(n_rows: int = MULTICHIP_ROWS,
                           shard_size: int = MULTICHIP_SHARD):
    """``drain_multichip`` leg (ISSUE 7): the swarm across N chips, both
    ways — a fleet of N single-chip agent processes (device-pinned via
    ``CHIP_SLICE``) and one dp=N mesh agent — against the 1-chip reference
    drain. Records per-mode rows/sec, ``n_chips``, per-agent shard counts,
    and ``scaling_efficiency`` = rows/sec at N ÷ (N × rows/sec at 1),
    asserting ≥ MULTICHIP_SCALING_FLOOR at N agents when the host has the
    cores to scale. Bit-identity of fleet and mesh results vs the 1-chip
    reference is always asserted."""
    import tempfile

    n = MULTICHIP_AGENTS
    extra = {"text_field": "text", "allow_fallback": False,
             "result_format": "columnar",
             "model_config": dict(MULTICHIP_MODEL), "topk": 5}
    leg: dict = {"rows": n_rows, "agents": n, "n_chips": n}
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "multichip.csv")
        with open(path, "w") as f:
            f.write("id,text\n")
            for i in range(n_rows):
                f.write(f'{i},"drain record {i} with a payload of text"\n')
        warm_file = os.path.join(td, "warm.json")
        with open(warm_file, "w") as f:
            json.dump([{
                "op": "map_classify_tpu",
                "payload": {**extra, "source_uri": path, "start_row": 0,
                            "shard_size": shard_size},
            }], f)
        results = {}
        for mode, n_agents, dev_per, mesh in (
            ("agents_1", 1, 1, ""),
            (f"agents_{n}", n, 1, ""),
            (f"mesh_dp{n}", 1, n, f"dp={n}"),
        ):
            rate, per_agent, res = _fleet_drain_mode(
                path, extra, warm_file,
                n_agents=n_agents, devices_per_agent=dev_per,
                mesh_shape=mesh, name_prefix=f"bench-{mode}",
                log_dir=os.path.join(td, f"logs_{mode}"),
                rows=n_rows, shard_size=shard_size,
            )
            leg[f"{mode}_rows_per_sec"] = round(rate, 1)
            results[mode] = res
            if n_agents > 1:
                leg["per_agent_shards"] = per_agent
                assert all(v > 0 for v in per_agent.values()), (
                    f"agent(s) got zero shards: {per_agent}"
                )
        for mode in (f"agents_{n}", f"mesh_dp{n}"):
            assert results[mode] == results["agents_1"], (
                f"{mode} drain diverged from the 1-chip reference"
            )
        leg["bit_identical"] = True
        eff = (
            leg[f"agents_{n}_rows_per_sec"]
            / (n * leg["agents_1_rows_per_sec"])
        )
        leg["scaling_efficiency"] = round(eff, 3)
        leg["host_cores"] = os.cpu_count()
        if (os.cpu_count() or 1) >= n:
            assert eff >= MULTICHIP_SCALING_FLOOR, (
                f"scaling_efficiency {eff:.3f} < {MULTICHIP_SCALING_FLOOR} "
                f"at {n} agents on {os.cpu_count()} cores"
            )
        else:
            # Fewer cores than agents: the bar is physically unreachable;
            # record why instead of asserting fiction.
            leg["scaling_gated"] = (
                f"{os.cpu_count()} cores < {n} agents; floor not asserted"
            )
        leg["rows_per_sec"] = leg[f"agents_{n}_rows_per_sec"]
    return leg


# Serving leg (ISSUE 15). Request mix: 90% short answers / 10% full-length
# — the interactive shape continuous batching exists for (short requests
# exit the running batch and free their slot; a static batch pays its
# longest rider for every seat). Recorded in the leg so the speedup is
# attributable to a stated workload, not a tuned one. MICRO_STEPS fuses
# decode iterations per dispatch where dispatch overhead would otherwise
# dominate (CPU smoke, tiny models); membership changes between chunks.
SERVE_BENCH_REQUESTS = 240
SERVE_BENCH_SLOTS = 8
SERVE_BENCH_SHORT_FRAC = 0.9
SERVE_BENCH_MICRO_STEPS = 4
SERVE_HTTP_DURATION_SEC = 8.0
SERVE_HTTP_RATE = 4.0

# Disaggregated-serving sub-leg (ISSUE 16). The mix is prefix-heavy on
# purpose: 3 of every 4 requests re-summarize one of a few shared
# documents (the millions-of-users shape — repeated system prompts and
# shared contexts), every 4th is a one-off. The shared rows hit the
# content-hashed prefix cache after the warm round; the one-offs keep the
# hit rate honest (expected 0.75 measured, bar ≥ 0.5).
SERVE_DISAGG_REQUESTS = 32
SERVE_DISAGG_DOCS = 4
SERVE_DISAGG_BULK_ROWS = 512
SERVE_DISAGG_BULK_SHARD = 64


def _bench_serving_beam(runtime):
    """Continuous-batching beam decode vs the static-batch beam baseline on
    the SAME seeded request stream (per-request token budgets drawn 90/10
    short/long): the static path decodes arrival-order batches of
    ``SERVE_BENCH_SLOTS`` requests, each batch running to its longest
    rider's budget (what a batch-serving stack without iteration-level
    membership does — BENCH_r05's beam leg shape); the continuous path runs
    the engine with per-slot limits, exits freeing slots for the backlog
    between steps. Per-request outputs equal a solo decode of that
    request's own budget (regression-tested in tests/test_serving.py);
    tok/s counts the REQUESTED token budgets both sides, so the speedup is
    useful-tokens wall-clock, not padding."""
    import jax
    import numpy as np

    from agent_tpu.models import seq2seq
    from agent_tpu.models.decoding import ContinuousBatcher
    from agent_tpu.models.tokenizer import BOS_ID, EOS_ID, PAD_ID

    smoke = runtime.platform != "tpu"
    cfg = seq2seq.Seq2SeqConfig() if not smoke else seq2seq.Seq2SeqConfig(
        d_model=128, n_heads=4, n_enc_layers=2, n_dec_layers=2, d_ff=256,
        max_src_len=64, max_tgt_len=64, dtype="float32",
    )
    n_req = SERVE_BENCH_REQUESTS
    K, slots = 4, SERVE_BENCH_SLOTS
    # Dispatch-bound smoke shapes amortize dispatch via fused micro-steps;
    # real TPU runs pure iteration-level stepping (buffer donation works).
    micro = SERVE_BENCH_MICRO_STEPS if smoke else 1
    src_len = 64
    T = cfg.max_tgt_len
    short = max(2, T // 32)
    rng = np.random.default_rng(5)
    limits = [
        short if rng.random() < SERVE_BENCH_SHORT_FRAC else T
        for _ in range(n_req)
    ]
    ids = rng.integers(4, cfg.vocab_size, (n_req, src_len)).astype(np.int32)
    mask = np.ones((n_req, src_len), dtype=np.int32)
    params = jax.device_put(
        seq2seq.init_params(cfg, model_id="bench-serving"),
        runtime.replicated(),
    )

    # ---- static baseline: arrival-order batches, padded to batch max ----
    gens: dict = {}

    def gen_for(n, max_new):
        key = (n, max_new)
        if key not in gens:
            gens[key] = jax.jit(
                lambda p, i, m, mn=max_new: seq2seq.beam_generate(
                    p, i, m, cfg, mn, num_beams=K,
                )
            )
        return gens[key]

    batches = [
        (slice(s, min(s + slots, n_req)),
         max(limits[s: min(s + slots, n_req)]))
        for s in range(0, n_req, slots)
    ]
    for n, mx in {(b.stop - b.start, mx) for b, mx in batches}:
        np.asarray(gen_for(n, mx)(params, ids[:n], mask[:n])[0])  # warm
    t0 = time.perf_counter()
    static_steps = 0
    for bat, mx in batches:
        np.asarray(gen_for(bat.stop - bat.start, mx)(
            params, ids[bat], mask[bat]
        )[0])
        static_steps += mx
    static_wall = time.perf_counter() - t0

    # ---- continuous engine on the identical stream ----
    enc_fn = jax.jit(
        lambda p, i, m: seq2seq.encode(p, i, m, cfg).astype(jax.numpy.float32)
    )
    enc_all = np.asarray(enc_fn(params, ids, mask))
    # ONE persistent engine, like the serving agent's: the warm pass pays
    # trace+compile, the measured pass is the steady-state cost.
    engine = ContinuousBatcher(
        seq2seq.make_positional_step(params, cfg),
        seq2seq.make_cache_factory(cfg),
        slots=slots, vocab_size=cfg.vocab_size, max_tokens=T,
        enc_len=src_len, d_model=cfg.d_model,
        start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID, num_beams=K,
        micro_steps=micro,
    )

    def run_engine():
        tickets = [
            engine.admit(enc_all[i], mask[i], limits[i], data=i)
            for i in range(n_req)
        ]
        s0 = engine.steps_run
        while engine.has_work():
            engine.step()
        return tickets, engine.steps_run - s0

    run_engine()  # warm the step/insert/prefill programs
    t0 = time.perf_counter()
    tickets, engine_steps = run_engine()
    cont_wall = time.perf_counter() - t0
    # Same numerator both sides: the tokens the requests ASKED for (the
    # static path additionally decoded short rows out to the batch max —
    # that padding waste is exactly the cost being measured).
    tokens = sum(t.steps for t in tickets)
    return {
        "requests": n_req,
        "num_beams": K,
        "slots": slots,
        "micro_steps": micro,
        "short_frac": SERVE_BENCH_SHORT_FRAC,
        "limit_short": short,
        "limit_long": T,
        "tokens": tokens,
        "static_steps": static_steps,
        "engine_steps": engine_steps,
        "static_tok_per_sec": round(tokens / static_wall, 1),
        "continuous_tok_per_sec": round(tokens / cont_wall, 1),
        "speedup_vs_static": round(static_wall / cont_wall, 3),
        "mean_occupancy": round(engine.mean_occupancy(), 2),
    }


def _audit_ttft_decomposition(controller):
    """TTFT decomposition audit shared by the serving legs (ISSUE 17):
    every completed record in the wide-event request log whose component
    chain is whole must telescope back to its measured TTFT within 10% —
    drift means the component histograms misattribute where time went.
    Returns ``(n_records, max_err, modal dominant component)``."""
    recs = [
        r for r in controller.requests_json(limit=2048)["requests"]
        if r.get("outcome") == "completed"
        and isinstance(r.get("ttft_ms"), (int, float))
        and r["ttft_ms"] > 0
        and len(r.get("components") or {}) == 6
    ]
    errs = [
        abs(sum(r["components"].values()) - r["ttft_ms"]) / r["ttft_ms"]
        for r in recs
    ]
    assert not errs or max(errs) <= 0.10, (
        f"TTFT components drifted {max(errs):.1%} from measured TTFT "
        f"(tolerance 10%)"
    )
    dom_counts: dict = {}
    for r in recs:
        d = r.get("dominant_component")
        if d:
            dom_counts[d] = dom_counts.get(d, 0) + 1
    return (
        len(recs),
        round(max(errs), 4) if errs else None,
        max(dom_counts, key=dom_counts.get) if dom_counts else None,
    )


def _bench_serving_disagg(runtime):
    """``serving.disagg`` sub-leg (ISSUE 16): the SAME seeded prefix-heavy
    greedy summarize stream driven through two in-process controller
    stacks while a bulk classify drain shares the lease loop —

    - **baseline**: the PR 15 colocated shape (dense per-slot KV, prefix
      cache off, prefill+decode fused in one ``serve_summarize`` job);
    - **disagg**: the ISSUE 16 stack (paged KV pool, content-hashed
      prefix cache, ``serve_prefill`` → dep-gated ``serve_decode``).

    The baseline run never caches, so one identity assert covers both
    acceptance bars at once: disagg-vs-colocated AND cached-vs-cold
    summaries are bit-identical (engine-vs-solo greedy identity is pinned
    separately in tests/test_serving.py + tests/test_paged_kv.py). The
    measured-round prefix hit rate is asserted ≥ 0.5; TTFT p50/p99, the
    p99/p50 tail ratio, and tok/s are recorded per stack."""
    import statistics as _stats
    import tempfile

    from agent_tpu.config import Config, ServeConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.ops import load_ops
    from agent_tpu.ops.serve_infer import reset_engines
    from agent_tpu.runtime.context import OpContext

    smoke = runtime.platform != "tpu"
    # Prefill-heavy shape ON PURPOSE (even in smoke): a deep encoder over a
    # long source vs a shallow few-step decode, so the leg measures what
    # the prefix cache actually buys — skipped prefill — rather than
    # host dispatch overhead. The shared documents fill the source bucket.
    s2s_cfg = None if not smoke else {
        "d_model": 128, "n_heads": 4, "n_enc_layers": 6, "n_dec_layers": 1,
        "d_ff": 512, "max_src_len": 256, "max_tgt_len": 8,
        "dtype": "float32",
    }
    cls_cfg = None if not smoke else {
        "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
        "max_len": 64, "dtype": "float32", "n_classes": 16,
    }
    n_req = SERVE_DISAGG_REQUESTS
    docs = [
        f"shared context document {d} " + "with common preamble content " * 8
        for d in range(SERVE_DISAGG_DOCS)
    ]

    def stream(round_idx):
        out = []
        for i in range(n_req):
            if i % 4 == 0:
                out.append(
                    f"one-off request r{round_idx} i{i} "
                    + "tail words " * 18
                )
            else:
                out.append(docs[i % SERVE_DISAGG_DOCS])
        return out

    def params():
        p = {"max_length": 4}
        if s2s_cfg:
            p["model_config"] = s2s_cfg
        return p

    bulk_extra = {"text_field": "text", "allow_fallback": False,
                  "result_format": "columnar"}
    if cls_cfg:
        bulk_extra["model_config"] = cls_cfg

    def drain(controller, handlers, ctx):
        """Lease loop until EVERYTHING (serving + bulk) drains. Returns the
        wall-clock instant the serving work finished — the bulk drain is
        identical constant work on both stacks, so folding its tail into
        the serving window would dilute the ratio being measured toward 1.
        """
        deadline = time.monotonic() + 600.0
        serve_done = None
        while True:
            controller._serve_pump()
            door = controller.serve_door
            if (serve_done is None and door.stats()["bucketed"] == 0
                    and not door.job_ids()):
                serve_done = time.perf_counter()
            lease = controller.lease(
                agent="bench-disagg",
                capabilities={"ops": sorted(handlers)},
                max_tasks=4,
            )
            if lease is None:
                if serve_done is not None and controller.drained():
                    controller._serve_pump()  # final reap
                    return serve_done
                assert time.monotonic() < deadline, controller.counts()
                time.sleep(0.002)
                continue
            for task in lease["tasks"]:
                result = handlers[task["op"]](task["payload"], ctx)
                controller.report(
                    lease_id=lease["lease_id"], job_id=task["id"],
                    job_epoch=task["job_epoch"],
                    status="succeeded" if result.get("ok") else "failed",
                    result=result,
                )

    def run_stack(serve_cfg, agent_serve_cfg):
        reset_engines()
        controller = Controller(lease_ttl_sec=600.0, serve=serve_cfg)
        # The decode knobs (KV layout, prefix cache) are AGENT-side config:
        # in production they arrive via SERVE_* env on the agent process.
        # The in-process lease loop injects them through the op context.
        ctx = OpContext(config=Config(serve=agent_serve_cfg))
        handlers = load_ops([
            "serve_summarize", "serve_prefill", "serve_decode",
            "map_classify_tpu",
        ])
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "bulk.csv")
            with open(path, "w") as f:
                f.write("id,text\n")
                for i in range(SERVE_DISAGG_BULK_ROWS):
                    f.write(f'{i},"drain record {i} with a payload"\n')
            # Warm round: compiles every bucket/batch shape AND seeds the
            # prefix cache with the shared documents (the warm round is the
            # cold pass — its shared rows all miss).
            controller.submit_csv_job(
                path, total_rows=SERVE_DISAGG_BULK_SHARD,
                shard_size=SERVE_DISAGG_BULK_SHARD,
                map_op="map_classify_tpu", extra_payload=bulk_extra,
            )
            for text in stream(0):
                controller.submit_infer("summarize", text, params=params())
            drain(controller, handlers, ctx)
            hits0 = controller._m_serve_prefix.value(event="hits")
            miss0 = controller._m_serve_prefix.value(event="misses")

            # Measured round: bulk drain + the prefix-heavy stream through
            # the same lease loop.
            controller.submit_csv_job(
                path, total_rows=SERVE_DISAGG_BULK_ROWS,
                shard_size=SERVE_DISAGG_BULK_SHARD,
                map_op="map_classify_tpu", extra_payload=bulk_extra,
            )
            t0 = time.perf_counter()
            rids = [
                controller.submit_infer("summarize", text, params=params())
                for text in stream(1)
            ]
            serve_done = drain(controller, handlers, ctx)
            wall = serve_done - t0
        snaps = []
        for rid in rids:
            snap = controller.infer_snapshot(rid)
            assert snap is not None and snap["state"] == "done", snap
            snaps.append(snap)
        ttfts = sorted(
            s["ttft_ms"] for s in snaps if s.get("ttft_ms") is not None
        )
        tokens = sum(s.get("tokens") or 0 for s in snaps)
        hits = controller._m_serve_prefix.value(event="hits") - hits0
        misses = controller._m_serve_prefix.value(event="misses") - miss0
        looked = hits + misses
        n_dec, max_err, dominant = _audit_ttft_decomposition(controller)
        out = {
            "requests": len(snaps),
            "ttft_decomposed_requests": n_dec,
            "ttft_decomposition_max_err": max_err,
            "ttft_dominant_component": dominant,
            "bulk_rows": SERVE_DISAGG_BULK_ROWS,
            "window_s": round(wall, 2),
            "tok_per_sec": round(tokens / wall, 1) if wall else None,
            "ttft_p50_ms": round(_stats.median(ttfts), 1) if ttfts else None,
            "ttft_p99_ms": round(
                ttfts[max(0, int(len(ttfts) * 0.99) - 1)], 1
            ) if ttfts else None,
            "prefix_hit_rate": round(hits / looked, 3) if looked else None,
            "kv_blocks_total": controller._m_serve_kv_total.value(),
        }
        if out["ttft_p50_ms"]:
            out["ttft_tail_ratio"] = round(
                out["ttft_p99_ms"] / out["ttft_p50_ms"], 2
            )
        summaries = [s["result"]["summary"] for s in snaps]
        ops_seen = {
            r.get("op") for r in controller.results().values()
            if isinstance(r, dict)
        }
        return out, summaries, ops_seen

    pr15 = ServeConfig(
        max_wait_ms=5.0, max_batch=8, kv_layout="dense",
        prefix_cache_enabled=False,
    )
    baseline, base_sums, _ = run_stack(pr15, pr15)
    issue16 = ServeConfig(max_wait_ms=5.0, max_batch=8, disaggregated=True)
    disagg, dis_sums, dis_ops = run_stack(issue16, issue16)
    assert base_sums == dis_sums, (
        "disaggregated/cached summaries diverged from the colocated cold run"
    )
    assert {"serve_prefill", "serve_decode"} <= dis_ops, dis_ops
    assert (disagg["prefix_hit_rate"] or 0.0) >= 0.5, (
        f"prefix hit rate {disagg['prefix_hit_rate']} < 0.5 on the seeded "
        "shared-prefix mix"
    )
    assert disagg["kv_blocks_total"] > 0, "paged KV pool gauge never set"
    leg = dict(disagg)
    leg["baseline"] = baseline
    leg["bit_identical"] = True
    if baseline.get("tok_per_sec") and disagg.get("tok_per_sec"):
        leg["vs_colocated"] = round(
            disagg["tok_per_sec"] / baseline["tok_per_sec"], 3
        )
    return leg


def _bench_serving(runtime):
    """``serving`` leg (ISSUE 15): loadgen-driven interactive classify +
    summarize requests against a REAL ``POST /v1/infer`` HTTP front door
    *while* a bulk classify drain runs through the same pipelined agent —
    TTFT p50/p99 and tok/s for the interactive traffic, the /v1/health
    verdict (per-tier SLOs judging it), plus the continuous-vs-static beam
    engine comparison above."""
    import statistics as _stats
    import tempfile
    import threading

    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.agent.pipeline import PipelineRunner
    from agent_tpu.config import AgentConfig, Config, ServeConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer
    from agent_tpu.loadgen import ArrivalPattern, LoadGen, TrafficClass
    from agent_tpu.loadgen import session_submitter

    smoke = runtime.platform != "tpu"
    s2s_cfg = None if not smoke else {
        "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
        "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
    }
    cls_cfg = None if not smoke else {
        "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
        "max_len": 64, "dtype": "float32", "n_classes": 16,
    }
    bulk_rows, bulk_shard = (2048, 256) if smoke else (DRAIN_ROWS,
                                                      DRAIN_SHARD_SIZE)
    duration = SERVE_HTTP_DURATION_SEC
    rate = SERVE_HTTP_RATE

    def params_for(op):
        if op == "summarize":
            p = {"max_length": 8}
            if s2s_cfg:
                p["model_config"] = s2s_cfg
            return p
        p = {"topk": 1}
        if cls_cfg:
            p["model_config"] = cls_cfg
        return p

    classes = [
        TrafficClass(
            name="infer_classify", op="classify", weight=2.0, route="infer",
            payload_fn=lambda rng, seq: {
                "text": f"interactive classify request {seq} "
                        + "with payload " * (seq % 3 + 1),
                "params": params_for("classify"),
            },
        ),
        TrafficClass(
            name="infer_summarize", op="summarize", weight=2.0,
            route="infer",
            payload_fn=lambda rng, seq: {
                "text": f"interactive summarize request {seq} "
                        + "with payload " * (seq % 3 + 1),
                "params": {
                    **params_for("summarize"),
                    "max_length": 4 + seq % 8,
                },
            },
        ),
    ]
    leg: dict = {}
    controller = Controller(
        lease_ttl_sec=600.0,
        serve=ServeConfig(max_wait_ms=20.0, max_batch=8),
    )
    server = ControllerServer(controller).start()
    try:
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="bench-serving",
            tasks=("serve_classify", "serve_summarize", "map_classify_tpu"),
            idle_sleep_sec=0.0,
        ))
        agent = Agent(config=cfg, session=requests.Session(),
                      runtime=runtime)
        agent._profile = {"tier": "bench"}
        runner = PipelineRunner(agent, depth=2)
        rt = threading.Thread(target=runner.run, daemon=True)
        rt.start()
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "bulk.csv")
            with open(path, "w") as f:
                f.write("id,text\n")
                for i in range(bulk_rows):
                    f.write(f'{i},"drain record {i} with a payload"\n')
            bulk_extra = {"text_field": "text", "allow_fallback": False,
                          "result_format": "columnar"}
            if cls_cfg:
                bulk_extra["model_config"] = cls_cfg
            # Warm the serving + bulk executables outside the window.
            sess = requests.Session()
            for op in ("classify", "summarize"):
                r = sess.post(server.url + "/v1/infer", json={
                    "op": op, "text": "warm the serving path",
                    "params": params_for(op),
                }, timeout=300)
                assert r.status_code == 200 and \
                    r.json()["state"] == "done", r.text
            controller.submit_csv_job(
                path, total_rows=bulk_shard, shard_size=bulk_shard,
                map_op="map_classify_tpu", extra_payload=bulk_extra,
            )
            while not controller.drained():
                time.sleep(0.02)

            # The measured window: bulk drain + open-loop interactive load.
            controller.submit_csv_job(
                path, total_rows=bulk_rows, shard_size=bulk_shard,
                map_op="map_classify_tpu", extra_payload=bulk_extra,
            )
            gen = LoadGen(classes, ArrivalPattern(rate), seed=7)
            t0 = time.perf_counter()
            stats = gen.run(
                session_submitter(sess, server.url), duration
            )
            req_ids = stats.job_ids()
            snaps = []
            for rid in req_ids:
                snap = controller.wait_infer(rid, 300.0)
                assert snap is not None and snap["state"] == "done", snap
                snaps.append(snap)
            window = time.perf_counter() - t0
            while not controller.drained():
                time.sleep(0.02)
            ttfts = sorted(
                s["ttft_ms"] for s in snaps if s.get("ttft_ms") is not None
            )
            tokens = sum(s.get("tokens") or 0 for s in snaps)
            from agent_tpu.obs.scrape import fetch_health

            health = fetch_health(server.url)
            n_dec, max_err, dominant = _audit_ttft_decomposition(controller)
            leg.update(
                requests=len(snaps),
                rejected=stats.total_rejected(),
                bulk_rows=bulk_rows,
                window_s=round(window, 2),
                ttft_p50_ms=round(_stats.median(ttfts), 1) if ttfts else None,
                ttft_p99_ms=round(
                    ttfts[max(0, int(len(ttfts) * 0.99) - 1)], 1
                ) if ttfts else None,
                tok_per_sec=round(tokens / window, 1) if window else None,
                health_verdict=(health or {}).get("verdict"),
                ttft_decomposed_requests=n_dec,
                ttft_decomposition_max_err=max_err,
                ttft_dominant_component=dominant,
            )
        agent.running = False
        rt.join(timeout=60)
    finally:
        server.stop()
    leg["beam"] = _bench_serving_beam(runtime)
    chips = runtime.n_devices if runtime.platform == "tpu" else 1
    leg["beam_tok_per_sec_per_chip"] = round(
        leg["beam"]["continuous_tok_per_sec"] / chips, 1
    )
    # Disaggregated prefill/decode + prefix-cache run (ISSUE 16) — its
    # bit-identity assertion failure must surface in the artifact without
    # killing the colocated numbers above.
    try:
        leg["disagg"] = _bench_serving_disagg(runtime)
    except Exception as exc:  # noqa: BLE001
        leg["disagg"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    return leg


DAG_WORKFLOWS = 40        # zipfian submissions per leg
DAG_FAN = 4               # classify shards per workflow (7 jobs each)
DAG_TEXTS = 256           # rows per classify shard (real forward pass)
DAG_POOL = 8              # distinct payload variants
DAG_ZIPF_S = 1.3          # head-heavy: most submissions repeat a variant


def _bench_dag_cache() -> dict:
    """Workflow DAG + result cache leg (ISSUE 19): a zipfian mix of
    fan-out/fan-in workflows (echo → DAG_FAN classify shards → collect →
    report) drained twice — cache OFF (every stage computes) and cache ON
    (repeated variants land as content-addressed hits) — through the
    in-process lease/report loop executing the REAL ops.

    Asserts the acceptance bar: the warm leg's hit rate clears 0.6 and its
    effective rows/sec is ≥2× the cold leg's. The hit count is
    deterministic given the seed (a function of the zipf draw, not
    timing); the classify forward pass supplies real per-shard compute, so
    the speedup measures cache-skipped work, not bookkeeping noise.
    """
    import random as _random

    from agent_tpu.config import FlowConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.loadgen import zipf_rank
    from agent_tpu.ops import load_ops
    from agent_tpu.runtime.context import OpContext

    tiny_cls = {
        "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
        "max_len": 64, "dtype": "float32", "n_classes": 16,
    }
    handlers = load_ops(["echo", "map_classify_tpu"])
    ctx = OpContext()

    def cls_payload(variant: int) -> dict:
        return {
            "texts": [
                f"classify row {i} variant {variant}"
                for i in range(DAG_TEXTS)
            ],
            "model_config": tiny_cls, "topk": 2,
            "result_format": "columnar",
        }

    def variant_doc(variant: int) -> dict:
        return {"stages": [
            {"name": "tok", "op": "echo", "payload": {"variant": variant}},
            {"name": "cls", "op": "map_classify_tpu",
             "payload": cls_payload(variant),
             "after": ["tok"], "fan_out": DAG_FAN, "collect": False},
            {"name": "acc", "op": "echo", "payload": {},
             "after": ["cls"]},
            {"name": "rep", "op": "echo", "payload": {"variant": variant},
             "after": ["acc"]},
        ]}

    # Pay the classify compile before either timed leg (production pays it
    # at boot; the cold leg must measure execution, not tracing).
    handlers["map_classify_tpu"](cls_payload(0), ctx)

    def run_leg(cache_enabled: bool):
        controller = Controller(
            flow=FlowConfig(cache_enabled=cache_enabled),
        )
        rng = _random.Random(19)
        jobs = 0
        t0 = time.perf_counter()
        for _ in range(DAG_WORKFLOWS):
            variant = zipf_rank(rng, DAG_POOL, DAG_ZIPF_S)
            out = controller.submit_workflow(variant_doc(variant))
            jobs += len(out["job_ids"])
            deadline = time.monotonic() + 300
            while True:
                lease = controller.lease(
                    "bench", {"ops": sorted(handlers)}, max_tasks=8,
                )
                if lease is None:
                    wj = controller.workflow_json(out["workflow_id"])
                    if wj["state"] != "running":
                        break
                    assert time.monotonic() < deadline, wj
                    continue
                for t in lease["tasks"]:
                    result = handlers[t["op"]](t["payload"], ctx)
                    controller.report(
                        lease["lease_id"], t["id"], t["job_epoch"],
                        "succeeded", result=result,
                    )
        wall = time.perf_counter() - t0
        stats = (
            controller.result_cache.stats()
            if controller.result_cache is not None else None
        )
        return jobs, wall, stats

    cold_jobs, cold_wall, _ = run_leg(cache_enabled=False)
    warm_jobs, warm_wall, stats = run_leg(cache_enabled=True)
    assert cold_jobs == warm_jobs, (cold_jobs, warm_jobs)
    cold_rate = cold_jobs / cold_wall
    warm_rate = warm_jobs / warm_wall
    hit_rate = stats["hit_rate"]
    speedup = warm_rate / cold_rate
    assert hit_rate >= 0.6, (
        f"zipfian mix hit rate {hit_rate:.2f} below 0.6 "
        f"(hits {stats['hits']}, misses {stats['misses']})"
    )
    assert speedup >= 2.0, (
        f"cache effective speedup {speedup:.2f}x below the 2x bar "
        f"(cold {cold_rate:.0f} rows/s, warm {warm_rate:.0f} rows/s)"
    )
    return {
        "workflows": DAG_WORKFLOWS,
        "stage_jobs": cold_jobs,
        "rows_per_sec": round(cold_rate, 1),
        "effective_rows_per_sec": round(warm_rate, 1),
        "effective_speedup": round(speedup, 3),
        "hit_rate": round(hit_rate, 4),
        "hits": stats["hits"],
        "misses": stats["misses"],
    }


def _bench_telemetry() -> dict:
    """Durable telemetry leg (ISSUE 20): the cost of persisting every
    sweep sample + scoring it for anomalies, and the forensic snapshot
    latency. Two numbers for the trend line:

    - ``tsdb_overhead_ratio`` — rows/sec on a pure-controller loopback
      drain with the on-disk store + detector + bundler enabled, over the
      same drain with them off (best-of-3 interleaved; ≈1.0 means the
      durable pipeline rides the sweep for free).
    - ``incident_capture_ms`` — median wall time of one correlated bundle
      snapshot (timeseries window + flight recorder + reqlog tail +
      status + health) on a controller with a warm ring.
    """
    import statistics
    import tempfile as _tempfile

    from agent_tpu.agent.app import Agent as _Agent
    from agent_tpu.chaos import LoopbackSession
    from agent_tpu.config import AgentConfig, Config, ObsConfig
    from agent_tpu.controller.core import Controller

    rows, shard = 65536, 1024

    def run_drain(tmp: str, enabled: bool, i: int) -> float:
        csv_path = os.path.join(tmp, "rows.csv")
        if not os.path.exists(csv_path):
            with open(csv_path, "w", encoding="utf-8") as f:
                f.write("id,text,risk\n")
                for r in range(rows):
                    f.write(f'{r},"record {r}",{(r % 13) * 0.5}\n')
        obs = ObsConfig(
            tsdb_dir=os.path.join(tmp, f"tsdb-{i}") if enabled else "",
            tsdb_interval_sec=0.1,
            anomaly_enabled=enabled, incident_enabled=enabled,
        )
        controller = Controller(journal_path=None, obs=obs)
        controller.submit_csv_job(
            csv_path, total_rows=rows, shard_size=shard,
            map_op="risk_accumulate", extra_payload={"field": "risk"},
        )
        cfg = Config(agent=AgentConfig(
            controller_url="http://loopback", agent_name=f"tel-{i}",
            tasks=("risk_accumulate",), max_tasks=4, idle_sleep_sec=0.0,
            error_backoff_sec=0.0,
        ))
        agent = _Agent(config=cfg, session=LoopbackSession(controller))
        agent._profile = {"tier": "bench"}
        t0 = time.perf_counter()
        deadline = time.monotonic() + 120
        while not controller.drained() and time.monotonic() < deadline:
            leased = agent.lease_once()
            if leased is None:
                controller.sweep()
                continue
            lease_id, tasks = leased
            for task in tasks:
                agent.run_task(lease_id, task)
        dt = time.perf_counter() - t0
        assert controller.drained(), controller.counts()
        controller.close()
        return rows / dt

    with _tempfile.TemporaryDirectory(prefix="bench_telemetry_") as tmp:
        best_on = best_off = 0.0
        for i in range(3):
            best_off = max(best_off, run_drain(tmp, False, i))
            best_on = max(best_on, run_drain(tmp, True, i))

        # Capture latency on a warm controller: populated ring + recorder.
        obs = ObsConfig(
            tsdb_dir=os.path.join(tmp, "tsdb-cap"),
            tsdb_interval_sec=0.0,
            incident_dir=os.path.join(tmp, "inc-cap"),
            incident_min_interval_sec=0.0,
        )
        controller = Controller(journal_path=None, obs=obs)
        for i in range(8):
            controller.submit("echo", {"i": i})
            controller.sweep()
        capture_ms = []
        for i in range(7):
            t0 = time.perf_counter()
            controller._capture_incident(
                "anomaly", f"bench-{i}", {"watch": "bench", "z": 10.0}
            )
            capture_ms.append((time.perf_counter() - t0) * 1e3)
        controller.close()

    return {
        "rows_per_sec_off": round(best_off, 1),
        "rows_per_sec_on": round(best_on, 1),
        "overhead_ratio": round(best_on / best_off, 4) if best_off else None,
        "incident_capture_ms": round(statistics.median(capture_ms), 3),
    }


def main() -> int:
    from agent_tpu.runtime.runtime import get_runtime

    runtime = get_runtime()
    n_chips = runtime.n_devices
    legs: dict = {}

    # 5 windows on the two noisiest legs (r3 spreads: flagship 11.7%,
    # long-ctx 14.0% at windows=3) — the median tightens, the spread field
    # shows it.
    flagship = _bench_classify_leg(
        runtime, batch=FLAGSHIP_BATCH, text_len=100, iters=FLAGSHIP_ITERS,
        windows=NOISY_WINDOWS,
    )
    legs["flagship"] = flagship
    # Per-chip normalization from the devices the LEG actually used
    # (ISSUE 7 satellite): real TPU legs engage the whole mesh; on host
    # backends the forced virtual devices share one CPU and are not chips —
    # dividing the host rate by 8 fabricated per-chip throughput. Fleet
    # legs carry their own n_chips.
    flagship_chips = n_chips if runtime.platform == "tpu" else 1
    flagship["n_chips_used"] = flagship_chips
    rows_per_sec_per_chip = flagship["rows_per_sec"] / flagship_chips

    for name, fn in (
        ("bert_base", lambda: _bench_bert_base(runtime)),
        ("bert_base_int8", lambda: _bench_bert_base_int8(
            runtime, legs.get("bert_base"))),
        ("moe", lambda: _bench_moe(runtime)),
        ("long_ctx", lambda: _bench_long_ctx(runtime)),
        ("train", lambda: _bench_train(runtime)),
        ("train_long_ctx", lambda: _bench_train_long_ctx(runtime)),
        ("summarize", lambda: _bench_summarize(runtime)),
        ("summarize_beam", lambda: _bench_summarize(runtime, num_beams=4)),
    ):
        try:
            legs[name] = fn()
        except Exception as exc:  # noqa: BLE001 — secondary legs must not
            # kill the line, but the cause must surface in the artifact.
            legs[name] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    # W8A16 weight-only decode: two legs (greedy + beam-4) from one runner,
    # speedups referenced against the bf16 legs recorded just above.
    try:
        w_greedy, w_beam = _bench_summarize_w8a16(
            runtime, legs.get("summarize"), legs.get("summarize_beam")
        )
        legs["summarize_w8a16"] = w_greedy
        legs["summarize_w8a16_beam"] = w_beam
    except Exception as exc:  # noqa: BLE001
        legs["summarize_w8a16"] = {
            "error": f"{type(exc).__name__}: {exc}"[:300]
        }
        legs["summarize_w8a16_beam"] = legs["summarize_w8a16"]

    import tempfile

    try:
        with tempfile.TemporaryDirectory() as td:
            legs["csv_index"] = {
                "mb_per_sec": round(_bench_csv_index(td), 1)
            }
    except Exception as exc:  # noqa: BLE001
        legs["csv_index"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    # Control-plane micro-bench (ISSUE 14): submits/sec, lease-grants/sec,
    # and the replay-compaction speedup — no jax, pure controller. Lives
    # in scripts/controller_bench.py so CI can run (and gate) it without
    # paying for the model legs.
    try:
        sys.path.insert(
            0,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts"),
        )
        import controller_bench

        # partitions=3: the ISSUE 18 aggregate-submits leg — N partition
        # processes journaling concurrently, the partitioned control
        # plane's scaling claim as a tracked number.
        ctrl = controller_bench.run_bench(partitions=3)
        legs["controller"] = {
            k: v for k, v in ctrl.items() if k != "detail"
        }
    except Exception as exc:  # noqa: BLE001
        legs["controller"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    # Workflow DAG + result cache (ISSUE 19): zipfian fan-out/fan-in mix,
    # cold vs cache-warm — asserts hit rate and the ≥2x effective-rate bar.
    try:
        legs["dag_cache"] = _bench_dag_cache()
    except Exception as exc:  # noqa: BLE001 — an AssertionError here is
        # the cache failing its own acceptance bar; it must surface.
        legs["dag_cache"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    # Durable telemetry (ISSUE 20): sweep-sample persistence overhead on a
    # pure-controller drain + the incident snapshot latency.
    try:
        legs["telemetry"] = _bench_telemetry()
    except Exception as exc:  # noqa: BLE001
        legs["telemetry"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    try:
        classify_drain, mixed_drain = _bench_drain(runtime)
        legs["drain"] = classify_drain
        legs["drain_mixed"] = mixed_drain
    except Exception as exc:  # noqa: BLE001 — an AssertionError here means
        # shards FAILED — a correctness signal, not an environment quirk.
        legs["drain"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    # Data-plane legs (ISSUE 6): staging-pool parallelism and the binary
    # shard wire, both against the same classify drain shape as `drain`.
    for name, fn in (
        ("drain_staged_parallel", lambda: _bench_drain_staged(runtime)),
        ("drain_binary_wire", lambda: _bench_drain_binary(runtime)),
        # Multi-chip swarm drain (ISSUE 7): fleet of N pinned agent
        # processes + dp=N mesh agent vs the 1-chip reference, scaling
        # efficiency asserted when the host has the cores.
        ("drain_multichip", _bench_drain_multichip),
        # Online serving (ISSUE 15): loadgen-driven POST /v1/infer traffic
        # concurrent with a bulk drain (TTFT p50/p99, tok/s, SLO verdict) +
        # the continuous-vs-static beam engine comparison.
        ("serving", lambda: _bench_serving(runtime)),
    ):
        try:
            legs[name] = fn()
        except Exception as exc:  # noqa: BLE001 — bit-identity assertion
            # failures must surface in the artifact, not kill the line.
            legs[name] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    baseline = 10_000.0  # BASELINE.md north star: ≥10k rows/sec/chip

    # Host-shape stamp + starved-leg marking (ISSUE 16 satellite): a
    # 1-core container CAN run the multichip/staged legs, but the numbers
    # measure core starvation, not the code (BENCH_r06 recorded
    # scaling_efficiency 0.187 that way). Stamp the cores into every
    # artifact and name the flat fields the regression checker must skip,
    # so starved rounds neither regress nor set baselines.
    host_cores = os.cpu_count() or 1
    starved_fields: list = []
    if host_cores < 4:  # the staged pool's parallel side runs 4 workers
        starved_fields.append("drain_staged_rows_per_sec")
        if isinstance(legs.get("drain_staged_parallel"), dict):
            legs["drain_staged_parallel"]["starved"] = True
    if host_cores < MULTICHIP_AGENTS:
        starved_fields += [
            "multichip_rows_per_sec", "multichip_scaling_efficiency",
        ]
        if isinstance(legs.get("drain_multichip"), dict):
            legs["drain_multichip"]["starved"] = True
    if host_cores < 4:  # 3 partition children + the bench parent
        starved_fields.append("controller_agg_submits_per_sec")

    print(
        json.dumps(
            {
                # Measurement config rides with the numbers so trend readers
                # can tell workload changes from framework changes.
                "bench_params": {
                    "windows": WINDOWS,
                    "noisy_windows": NOISY_WINDOWS,  # flagship + long_ctx
                    "classify_batch": FLAGSHIP_BATCH,
                    "classify_iters": FLAGSHIP_ITERS,
                    "bert_batch": BERT_BATCH,
                    "bert_config": BERT_CONFIG,
                    "long_ctx_batch": LONG_CTX_BATCH,
                    "summarize_batch": SUMMARIZE_BATCH,
                    "summarize_max_new": SUMMARIZE_MAX_NEW,
                    "summarize_iters": SUMMARIZE_ITERS,
                    "agreement_rows": AGREEMENT_ROWS,
                    "train_batch": TRAIN_BATCH,
                    "train_steps": TRAIN_STEPS,
                    "drain_rows": DRAIN_ROWS,
                    "drain_shard_size": DRAIN_SHARD_SIZE,
                    "drain_summarize_rows": DRAIN_SUMMARIZE_ROWS,
                    "multichip_agents": MULTICHIP_AGENTS,
                    "multichip_rows": MULTICHIP_ROWS,
                    "multichip_shard_size": MULTICHIP_SHARD,
                    "serve_bench_requests": SERVE_BENCH_REQUESTS,
                    "serve_bench_slots": SERVE_BENCH_SLOTS,
                    "serve_bench_short_frac": SERVE_BENCH_SHORT_FRAC,
                    "serve_http_duration_sec": SERVE_HTTP_DURATION_SEC,
                    "serve_http_rate": SERVE_HTTP_RATE,
                    "serve_disagg_requests": SERVE_DISAGG_REQUESTS,
                    "serve_disagg_docs": SERVE_DISAGG_DOCS,
                },
                "host_cores": host_cores,
                "starved_fields": starved_fields,
                "metric": "map_classify_tpu rows/sec/chip",
                "value": round(rows_per_sec_per_chip, 1),
                "unit": "rows/s/chip",
                "vs_baseline": round(rows_per_sec_per_chip / baseline, 3),
                "platform": runtime.platform,
                "device_kind": getattr(
                    runtime.devices[0], "device_kind", None
                ),
                "n_chips": n_chips,
                "legs": legs,
                # Flat trend fields (r01/r02 continuity).
                "classify_p50_batch_ms": flagship["p50_batch_ms"],
                "bert_base_rows_per_sec": legs["bert_base"].get("rows_per_sec"),
                "bert_base_mfu": legs["bert_base"].get("mfu"),
                "bert_base_int8_rows_per_sec": legs["bert_base_int8"].get(
                    "rows_per_sec"
                ),
                "int8_agreement_top1": legs["bert_base_int8"].get(
                    "agreement_top1"
                ),
                "moe_rows_per_sec": legs["moe"].get("rows_per_sec"),
                "long_ctx_rows_per_sec": legs["long_ctx"].get("rows_per_sec"),
                "train_examples_per_sec": legs["train"].get("examples_per_sec"),
                "train_mfu": legs["train"].get("mfu"),
                "train_long_ctx_mfu": legs["train_long_ctx"].get("mfu"),
                "summarize_decode_tok_per_sec": legs["summarize"].get(
                    "decode_tok_per_sec"
                ),
                "summarize_beam_tok_per_sec": legs["summarize_beam"].get(
                    "decode_tok_per_sec"
                ),
                "summarize_w8a16_tok_per_sec": legs["summarize_w8a16"].get(
                    "decode_tok_per_sec"
                ),
                "summarize_w8a16_beam_tok_per_sec": legs[
                    "summarize_w8a16_beam"
                ].get("decode_tok_per_sec"),
                "w8a16_vs_bf16": legs["summarize_w8a16_beam"].get(
                    "w8a16_vs_bf16"
                ),
                "w8a16_agreement_token": legs["summarize_w8a16_beam"].get(
                    "agreement_token"
                ),
                "w8a16_agreement_control": legs["summarize_w8a16_beam"].get(
                    "agreement_control_token"
                ),
                "flash_vs_dense_8k": legs["long_ctx"].get("flash_vs_dense_8k"),
                "csv_index_mb_per_sec": legs["csv_index"].get("mb_per_sec"),
                "e2e_drain_rows_per_sec": legs["drain"].get("rows_per_sec"),
                "drain_staged_rows_per_sec": legs["drain_staged_parallel"]
                .get("rows_per_sec"),
                "wire_bytes_per_row": legs["drain_binary_wire"]
                .get("bytes_per_row"),
                "wire_shrink_x": legs["drain_binary_wire"]
                .get("wire_shrink_x"),
                # Multi-chip flat fields (ISSUE 7): the trajectory finally
                # records n_chips > 1 and the scaling it buys.
                "multichip_rows_per_sec": legs["drain_multichip"]
                .get("rows_per_sec"),
                "multichip_scaling_efficiency": legs["drain_multichip"]
                .get("scaling_efficiency"),
                "multichip_n_chips": legs["drain_multichip"].get("n_chips"),
                # Fleet health flat fields (ISSUE 8): verdict + per-op SLO
                # attainment and live MFU off GET /v1/health for the mixed
                # drain leg.
                "health_verdict": legs.get("drain_mixed", {})
                .get("health_verdict"),
                "slo_attainment_classify": (
                    legs.get("drain_mixed", {}).get("slo_attainment") or {}
                ).get("map_classify_tpu"),
                "slo_attainment_summarize": (
                    legs.get("drain_mixed", {}).get("slo_attainment") or {}
                ).get("map_summarize"),
                "mfu_classify": (
                    legs.get("drain_mixed", {}).get("mfu") or {}
                ).get("map_classify_tpu"),
                "mfu_summarize": (
                    legs.get("drain_mixed", {}).get("mfu") or {}
                ).get("map_summarize"),
                # Resource accounting flat fields (ISSUE 9): billed device
                # seconds + rows off GET /v1/usage for the mixed drain leg.
                "usage_device_seconds": legs.get("drain_mixed", {})
                .get("usage_device_seconds"),
                "usage_rows": legs.get("drain_mixed", {}).get("usage_rows"),
                # Serving flat fields (ISSUE 15): interactive TTFT/tok-per-
                # sec measured concurrently with a bulk drain, plus the
                # continuous-batching beam engine vs the static-batch
                # baseline on the same request stream.
                "serving_ttft_p50_ms": legs["serving"].get("ttft_p50_ms"),
                "serving_ttft_p99_ms": legs["serving"].get("ttft_p99_ms"),
                "serving_tok_per_sec": legs["serving"].get("tok_per_sec"),
                "serving_beam_tok_per_sec": (
                    legs["serving"].get("beam") or {}
                ).get("continuous_tok_per_sec"),
                "serving_beam_speedup_vs_static": (
                    legs["serving"].get("beam") or {}
                ).get("speedup_vs_static"),
                # Request-level observability flat fields (ISSUE 17): the
                # modal dominant TTFT component across the leg's completed
                # requests (a string — the regression judge skips it) and
                # the worst component-sum drift vs measured TTFT.
                "serving_ttft_dominant_component": legs["serving"]
                .get("ttft_dominant_component"),
                "serving_ttft_decomposition_max_err": legs["serving"]
                .get("ttft_decomposition_max_err"),
                # Disaggregated serving flat fields (ISSUE 16): the
                # prefix-heavy mix through the paged-KV + prefix-cache +
                # prefill/decode-split stack, vs the colocated cold
                # baseline on the identical stream.
                "serving_disagg_tok_per_sec": (
                    legs["serving"].get("disagg") or {}
                ).get("tok_per_sec"),
                "serving_disagg_ttft_p99_ms": (
                    legs["serving"].get("disagg") or {}
                ).get("ttft_p99_ms"),
                "serving_disagg_vs_colocated": (
                    legs["serving"].get("disagg") or {}
                ).get("vs_colocated"),
                "serving_prefix_hit_rate": (
                    legs["serving"].get("disagg") or {}
                ).get("prefix_hit_rate"),
                # Control-plane flat fields (ISSUE 14): the controller
                # ceiling as tracked numbers — submit/lease throughput and
                # the snapshot-compaction replay speedup.
                "controller_submits_per_sec": legs["controller"]
                .get("submits_per_sec"),
                "controller_lease_grants_per_sec": legs["controller"]
                .get("lease_grants_per_sec"),
                "controller_tasks_leased_per_sec": legs["controller"]
                .get("tasks_leased_per_sec"),
                "controller_replay_events_per_sec": legs["controller"]
                .get("replay_events_per_sec"),
                "controller_replay_compacted_sec": legs["controller"]
                .get("replay_compacted_sec"),
                "controller_replay_speedup": legs["controller"]
                .get("replay_speedup"),
                # Partitioned aggregate (ISSUE 18): N concurrent
                # partition processes vs one — starved-stamped on
                # < 4-core hosts above.
                "controller_agg_submits_per_sec": legs["controller"]
                .get("agg_submits_per_sec"),
                "controller_agg_speedup_vs_single": legs["controller"]
                .get("agg_speedup_vs_single"),
                # Workflow DAG + result cache flat fields (ISSUE 19): cold
                # DAG drain throughput, the zipfian mix's dedupe hit rate,
                # and the effective-rate multiple the cache buys.
                "dag_rows_per_sec": legs["dag_cache"].get("rows_per_sec"),
                "cache_hit_rate": legs["dag_cache"].get("hit_rate"),
                "cache_effective_speedup": legs["dag_cache"]
                .get("effective_speedup"),
                # Durable telemetry flat fields (ISSUE 20): the throughput
                # cost of persisting+scoring every sweep sample (≈1.0 =
                # free) and the forensic bundle snapshot latency.
                "tsdb_overhead_ratio": legs["telemetry"]
                .get("overhead_ratio"),
                "incident_capture_ms": legs["telemetry"]
                .get("incident_capture_ms"),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
