"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline: rows/sec/chip on ``map_classify_tpu`` (the BASELINE.json north-star
metric; target ≥10,000 rows/sec/chip on the flagship encoder). The op is
measured end to end — host tokenization, padding, device transfer, jitted
forward, top-k — because that is what a leased task pays; compile time is
excluded by warmup (the executable cache makes it a once-per-process cost,
reference handle-singleton semantics).

Extra fields in the same JSON object record secondary numbers (batch latency
p50, summarize decode tokens/sec, CSV index build MB/s) for trend tracking.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Measurement configuration — single definitions shared by the bench
# functions and the bench_params field in the output line, so the recorded
# config can never drift from the executed one.
CLASSIFY_BATCH = 8192
CLASSIFY_ITERS = 10
CLASSIFY_WINDOWS = 2
SUMMARIZE_BATCH = 256
SUMMARIZE_MAX_NEW = 32
DRAIN_ROWS = 65_536
DRAIN_SHARD_SIZE = 8192


def _bench_classify(runtime, batch: int = CLASSIFY_BATCH,
                    text_len: int = 100, iters: int = CLASSIFY_ITERS):
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext

    classify = get_op("map_classify_tpu")
    ctx = OpContext(runtime=runtime)
    texts = [
        ("sample record %06d " % i) * max(1, text_len // 20)
        for i in range(batch)
    ]
    payload = {"texts": texts, "topk": 5, "allow_fallback": False}

    out = classify(payload, ctx)  # warmup: tokenize + compile + run
    assert out["ok"] is True and out.get("fallback") is None, out

    # Best of two measurement windows: the transport to the chip adds
    # load-dependent noise; the better window reflects the framework.
    best_rows_per_sec, best_p50 = 0.0, 0.0
    for _ in range(CLASSIFY_WINDOWS):
        lat = []
        t0 = time.perf_counter()
        for _ in range(iters):
            it0 = time.perf_counter()
            out = classify(payload, ctx)
            lat.append(time.perf_counter() - it0)
        wall = time.perf_counter() - t0
        assert out["ok"] is True, out
        rows_per_sec = batch * iters / wall
        if rows_per_sec > best_rows_per_sec:
            lat.sort()
            best_rows_per_sec = rows_per_sec
            best_p50 = lat[len(lat) // 2] * 1000.0
    return best_rows_per_sec, best_p50


def _bench_summarize(runtime, batch: int = SUMMARIZE_BATCH,
                     max_new: int = SUMMARIZE_MAX_NEW):
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext

    summarize = get_op("map_summarize")
    ctx = OpContext(runtime=runtime)
    payload = {
        "texts": ["a document to compress " * 20] * batch,
        "max_length": max_new,
    }
    summarize(payload, ctx)  # warmup/compile
    t0 = time.perf_counter()
    out = summarize(payload, ctx)
    dt = time.perf_counter() - t0
    assert out["ok"] is True, out
    return batch * max_new / dt  # decode tokens/sec (upper bound: no early EOS)


def _bench_csv_index(tmpdir: str, n_rows: int = 200_000):
    from agent_tpu.data.csv_index import CsvIndex

    path = os.path.join(tmpdir, "bench_rows.csv")
    with open(path, "w") as f:
        f.write("id,text,risk\n")
        for i in range(n_rows):
            f.write(f'{i},"record {i} with some text payload",{i % 97}\n')
    size_mb = os.path.getsize(path) / 1e6
    t0 = time.perf_counter()
    index = CsvIndex.for_file(path)  # fresh temp file ⇒ cold index build
    dt = time.perf_counter() - t0
    assert index.n_data_rows == n_rows, index.n_data_rows
    return size_mb / dt


def _bench_drain(runtime, n_rows: int = DRAIN_ROWS,
                 shard_size: int = DRAIN_SHARD_SIZE):
    """Framework-level drain: controller shards a CSV into classify tasks,
    one agent drains them over real HTTP — the BASELINE.json 10M-row drain
    shape at bench scale. Returns end-to-end rows/sec."""
    import tempfile

    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "drain.csv")
        with open(path, "w") as f:
            f.write("id,text,risk\n")
            for i in range(n_rows):
                f.write(f'{i},"drain record {i} with a payload of text",{i % 89}\n')

        controller = Controller(lease_ttl_sec=600.0)
        with ControllerServer(controller) as server:
            cfg = Config(
                agent=AgentConfig(
                    controller_url=server.url,
                    agent_name="bench-drain",
                    tasks=("map_classify_tpu",),
                    idle_sleep_sec=0.0,
                )
            )
            agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
            agent._profile = {"tier": "bench"}

            # Warm the executable cache outside the timed window (compile is a
            # once-per-process cost, reference handle-singleton semantics).
            controller.submit_csv_job(
                path, total_rows=shard_size, shard_size=shard_size,
                map_op="map_classify_tpu",
                extra_payload={"text_field": "text", "allow_fallback": False,
                               "result_format": "columnar"},
            )
            while not controller.drained():
                agent.step()

            controller.submit_csv_job(
                path, total_rows=n_rows, shard_size=shard_size,
                map_op="map_classify_tpu",
                extra_payload={"text_field": "text", "allow_fallback": False,
                               "result_format": "columnar"},
            )
            t0 = time.perf_counter()
            while not controller.drained():
                agent.step()
            wall = time.perf_counter() - t0
            counts = controller.counts()
            assert counts.get("failed", 0) == 0, counts
            # Soft-failed shards are recorded SUCCEEDED — check result bodies
            # so a drain that classified nothing can't report throughput.
            bad = [
                r for r in controller.results().values()
                if not (isinstance(r, dict) and r.get("ok") is True)
            ]
            assert not bad, f"{len(bad)} shards returned non-ok results"
    return n_rows / wall


def main() -> int:
    from agent_tpu.runtime.runtime import get_runtime

    runtime = get_runtime()
    n_chips = runtime.n_devices

    rows_per_sec, p50_ms = _bench_classify(runtime)
    rows_per_sec_per_chip = rows_per_sec / n_chips

    try:
        decode_tok_per_sec = _bench_summarize(runtime)
    except Exception:  # noqa: BLE001 — secondary metric must not kill the line
        decode_tok_per_sec = None

    import tempfile

    try:
        with tempfile.TemporaryDirectory() as td:
            csv_mb_per_sec = _bench_csv_index(td)
    except Exception:  # noqa: BLE001
        csv_mb_per_sec = None

    drain_error = None
    try:
        drain_rows_per_sec = _bench_drain(runtime)
    except Exception as exc:  # noqa: BLE001 — metric must not kill the line,
        # but the cause must surface (an AssertionError here means shards
        # FAILED — a correctness signal, not an environment quirk).
        drain_rows_per_sec = None
        drain_error = f"{type(exc).__name__}: {exc}"[:300]

    baseline = 10_000.0  # BASELINE.md north star: ≥10k rows/sec/chip
    print(
        json.dumps(
            {
                # Measurement config rides with the numbers so trend readers
                # can tell workload changes from framework changes.
                "bench_params": {
                    "classify_batch": CLASSIFY_BATCH,
                    "classify_iters": CLASSIFY_ITERS,
                    "classify_windows": CLASSIFY_WINDOWS,
                    "summarize_batch": SUMMARIZE_BATCH,
                    "summarize_max_new": SUMMARIZE_MAX_NEW,
                    "drain_rows": DRAIN_ROWS,
                    "drain_shard_size": DRAIN_SHARD_SIZE,
                },
                "metric": "map_classify_tpu rows/sec/chip",
                "value": round(rows_per_sec_per_chip, 1),
                "unit": "rows/s/chip",
                "vs_baseline": round(rows_per_sec_per_chip / baseline, 3),
                "platform": runtime.platform,
                "n_chips": n_chips,
                "classify_p50_batch_ms": round(p50_ms, 2),
                "summarize_decode_tok_per_sec": (
                    round(decode_tok_per_sec, 1) if decode_tok_per_sec else None
                ),
                "csv_index_mb_per_sec": (
                    round(csv_mb_per_sec, 1) if csv_mb_per_sec else None
                ),
                "e2e_drain_rows_per_sec": (
                    round(drain_rows_per_sec, 1) if drain_rows_per_sec else None
                ),
                **({"drain_error": drain_error} if drain_error else {}),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
